"""Profiler (reference: src/profiler/* + python/mxnet/profiler.py — chrome
trace emission, aggregate summaries; SURVEY.md §5.1).

TPU-native: host-side events are recorded in chrome://tracing format exactly
like the reference; device-side, `profiler_start/stop` also drives the JAX/XLA
TPU profiler (jax.profiler) whose traces carry the MXU/HBM detail, replacing
CUDA kernel events.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional

__all__ = ["set_config", "set_state", "profiler_set_config", "profiler_set_state",
           "start", "stop", "pause", "resume", "dump", "dumps", "Task", "Frame",
           "Event", "Counter", "Marker", "Domain", "scope"]

_lock = threading.Lock()
_events: List[dict] = []
_state = {"running": False, "filename": "profile.json", "aggregate": False,
          "jax_trace_dir": None, "t0": None}
_counters: Dict[str, float] = {}


def set_config(filename="profile.json", profile_all=False, profile_symbolic=False,
               profile_imperative=False, profile_memory=False, profile_api=False,
               aggregate_stats=False, continuous_dump=False, **kwargs):
    """Reference: MXSetProcessProfilerConfig.

    All category flags persist (an earlier version silently dropped
    ``profile_memory``/``profile_api``/``continuous_dump``): the memory and
    api flags gate their event categories in :func:`_emit`, and
    ``continuous_dump`` makes :func:`stop` flush the trace to ``filename``
    automatically (the reference's keep-dumping-without-MXDumpProfile mode).
    """
    _state["filename"] = filename
    _state["aggregate"] = aggregate_stats
    _state["imperative"] = bool(profile_imperative or profile_all)
    _state["symbolic"] = bool(profile_symbolic or profile_all)
    _state["memory"] = bool(profile_memory or profile_all)
    _state["api"] = bool(profile_api or profile_all)
    _state["continuous_dump"] = bool(continuous_dump)


profiler_set_config = set_config


def set_state(state="stop", profile_process="worker"):
    if state == "run":
        start()
    else:
        stop()


profiler_set_state = set_state


def start(profile_process="worker"):
    already = _state["running"]
    _state["running"] = True
    _state["t0"] = time.perf_counter()
    trace_dir = os.environ.get("TPUMX_JAX_TRACE_DIR")
    # idempotent like the reference (set_state('run') twice is legal): a
    # second start must not re-enter jax.profiler.start_trace
    if trace_dir and not (already and _state.get("jax_trace_dir")):
        import jax

        _state["jax_trace_dir"] = trace_dir
        jax.profiler.start_trace(trace_dir)


def stop(profile_process="worker"):
    _state["running"] = False
    if _state.get("jax_trace_dir"):
        import jax

        jax.profiler.stop_trace()
        _state["jax_trace_dir"] = None
    if _state.get("continuous_dump"):
        dump()


def pause(profile_process="worker"):
    _state["running"] = False


def resume(profile_process="worker"):
    _state["running"] = True


def _op_profiling() -> bool:
    """True when per-op imperative profiling is active — checked by
    ndarray.invoke (the ProfileOperator analogue, threaded_engine.h:337)."""
    return _state["running"] and _state.get("imperative", False)


# event categories gated by their set_config flag; anything else (counters,
# python scopes, serving spans) records whenever the profiler runs
_GATED_CATS = {"memory": "memory", "api": "api"}


def _emit(ph, name, cat, ts=None, dur=None, args=None, force=False):
    if not _state["running"] and not force:
        return
    flag = _GATED_CATS.get(cat)
    if flag is not None and not _state.get(flag, False):
        return
    ev = {"ph": ph, "name": name, "cat": cat, "pid": os.getpid(),
          "tid": threading.get_ident(),
          "ts": (ts if ts is not None else time.perf_counter() * 1e6)}
    if dur is not None:
        ev["dur"] = dur
    if args:
        ev["args"] = args
    with _lock:
        _events.append(ev)


def dumps(reset=False, format="table"):
    """Aggregate summary string (reference: MXAggregateProfileStatsPrint);
    format="json" returns the chrome://tracing event JSON instead."""
    if format == "json":
        with _lock:
            out = json.dumps({"traceEvents": list(_events),
                              "displayTimeUnit": "ms"})
            if reset:
                _events.clear()
        return out
    agg = defaultdict(lambda: [0, 0.0])
    with _lock:
        for ev in _events:
            if ev["ph"] == "X":
                agg[ev["name"]][0] += 1
                agg[ev["name"]][1] += ev.get("dur", 0.0)
    lines = [f"{'Name':<40}{'Count':>10}{'Total(us)':>15}"]
    for name, (cnt, total) in sorted(agg.items(), key=lambda kv: -kv[1][1]):
        lines.append(f"{name:<40}{cnt:>10}{total:>15.1f}")
    if reset:
        with _lock:
            _events.clear()
    return "\n".join(lines)


def dump(finished=True, profile_process="worker"):
    """Write chrome://tracing JSON (reference: MXDumpProfile)."""
    with _lock:
        data = {"traceEvents": list(_events), "displayTimeUnit": "ms"}
    with open(_state["filename"], "w") as f:
        json.dump(data, f)


class Domain:
    def __init__(self, name):
        self.name = name

    def __repr__(self):
        return f"Domain({self.name})"


class Task:
    def __init__(self, domain, name):
        self.domain = domain
        self.name = name
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter() * 1e6

    def stop(self):
        if self._t0 is not None:
            _emit("X", self.name, self.domain.name, ts=self._t0,
                  dur=time.perf_counter() * 1e6 - self._t0)
            self._t0 = None  # a second stop() must not emit a phantom span


Frame = Task


class Event(Task):
    pass


class Counter:
    def __init__(self, domain, name, value=0):
        self.domain = domain
        self.name = name
        self._value = value
        # per-counter lock: increment/decrement are read-modify-write and
        # raced from multiple threads (serving worker + submitters); the
        # unguarded `self._value + delta` lost updates
        self._vlock = threading.Lock()

    def set_value(self, value):
        with self._vlock:
            self._value = value
        _emit("C", self.name, self.domain.name, args={self.name: value})

    def increment(self, delta=1):
        with self._vlock:
            self._value += delta
            value = self._value
        _emit("C", self.name, self.domain.name, args={self.name: value})

    def decrement(self, delta=1):
        self.increment(-delta)

    __iadd__ = lambda self, d: (self.increment(d), self)[1]
    __isub__ = lambda self, d: (self.decrement(d), self)[1]


class Marker:
    def __init__(self, domain, name):
        self.domain = domain
        self.name = name

    def mark(self, scope="process"):
        _emit("i", self.name, self.domain.name, args={"scope": scope})


class scope:
    """Context manager timing a region as one trace slice."""

    def __init__(self, name, cat="python"):
        self._name = name
        self._cat = cat

    def __enter__(self):
        self._t0 = time.perf_counter() * 1e6
        self._active = _state["running"]  # capture at entry: a span that ran
        return self                        # under a live profiler is recorded
                                           # even if stop() lands inside it

    def __exit__(self, *exc):
        # the captured entry state decides BOTH ways: a span entered under a
        # live profiler is recorded even if stop() landed inside it
        # (force=True, never a flip of the shared running flag, which would
        # race other threads' emits past stop()); one entered while the
        # profiler was stopped stays unrecorded even if start() landed
        # before exit — its t0 predates the trace and would emit a phantom
        # pre-start() slice
        if self._active:
            _emit("X", self._name, self._cat, ts=self._t0,
                  dur=time.perf_counter() * 1e6 - self._t0, force=True)
