"""Async distributed checkpointing: atomic, checksummed, retained.

Design (docs/fault_tolerance.md):

- a checkpoint is a DIRECTORY ``ckpt-<step:010d>/`` holding one ``.npz``
  per array group (params, aux), a pickled optimizer-state tree, and a
  ``manifest.json`` carrying the step, the train metadata (epoch/batch,
  optimizer counters, loss-scaler state, RNG key) and a sha256 per file;
- commit is atomic: everything is written into a ``.tmp-…`` sibling and
  ``os.rename``'d into place — a crash mid-write leaves a stale tmp dir
  (garbage-collected on the next save), never a half-valid checkpoint;
- saves are ASYNC by default: the caller captures device-side copies of
  the donated fused-step buffers (cheap device-to-device copies — the
  train step never stalls on host transfer or file IO) and hands them to
  one background writer thread, which does the device→host transfer,
  serialization, hashing and the atomic rename.  At most one save is in
  flight; a save landing while the writer is busy is SKIPPED (counted) —
  a slow disk degrades checkpoint frequency, not step time;
- retention: after each commit the newest ``keep`` checkpoints survive,
  older ones (and stale tmp dirs) are deleted;
- restore scans newest-first and VALIDATES each candidate (manifest
  parses, every file present, every sha256 matches) before trusting it: a
  corrupt or truncated newest checkpoint is skipped — with a warning and a
  ``checkpoint_restore_fallbacks_total`` count — in favor of the previous
  retained one.

Registry metrics (docs/observability.md): ``checkpoint_save_seconds``,
``checkpoint_save_bytes_total``, ``checkpoint_saves_total{mode}``,
``checkpoint_save_skipped_total``, ``checkpoint_save_failures_total``,
``checkpoint_last_step``, ``checkpoint_restores_total``,
``checkpoint_restore_seconds``, ``checkpoint_restore_fallbacks_total``.
Spans: ``checkpoint.save_async`` (writer thread), ``checkpoint.save_sync``,
``checkpoint.restore``.
"""
from __future__ import annotations

import json
import logging
import os
import pickle
import queue
import re
import shutil
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as _np

from ..base import MXNetError
from .integrity import file_sha256

__all__ = ["CheckpointManager", "CheckpointInfo"]

_logger = logging.getLogger("mxnet_tpu.checkpoint")

_CKPT_RE = re.compile(r"^ckpt-(\d{10})$")
_OPT_FILE = "opt_state.pkl"
_MANIFEST = "manifest.json"


def _registry():
    from ..observability import registry

    return registry()


def _json_safe(obj):
    """Convert device/numpy scalars and arrays inside checkpoint meta to
    plain Python — runs on the WRITER thread, so a device scalar in the
    meta (e.g. the AMP loss-scaler state) costs the fit thread nothing."""
    if isinstance(obj, dict):
        return {str(k): _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    if isinstance(obj, (str, bool, int, float)) or obj is None:
        return obj
    a = _np.asarray(obj)
    if a.ndim == 0:
        return a.item()
    return a.tolist()


def _span(name):
    from ..observability import span

    return span(name, cat="checkpoint")


class CheckpointInfo:
    """One committed checkpoint: path + parsed manifest."""

    __slots__ = ("path", "step", "manifest")

    def __init__(self, path: str, step: int, manifest: dict):
        self.path = path
        self.step = step
        self.manifest = manifest

    @property
    def meta(self) -> dict:
        return self.manifest.get("meta", {})

    def __repr__(self):
        return f"CheckpointInfo(step={self.step}, path={self.path!r})"


class CheckpointManager:
    """Atomic, checksummed, retained checkpoints under one directory.

    ``save(arrays, opt_tree, meta, step)`` — arrays is ``{group_name:
    {key: array}}`` (device or host arrays; converted to numpy on the
    writer), ``opt_tree`` an arbitrary pickleable pytree of arrays (the
    optimizer-state structure), ``meta`` a JSON-safe dict.
    """

    def __init__(self, directory: str, keep: int = 3):
        if keep < 1:
            raise MXNetError(f"CheckpointManager: keep must be >= 1, "
                             f"got {keep}")
        self.directory = os.path.abspath(directory)
        self.keep = int(keep)
        os.makedirs(self.directory, exist_ok=True)
        self._writer: Optional[threading.Thread] = None
        self._queue: "queue.Queue" = queue.Queue(maxsize=1)
        self._idle = threading.Event()
        self._idle.set()
        self._closed = False
        self._lock = threading.Lock()
        reg = _registry()
        self._h_save = reg.histogram(
            "checkpoint_save_seconds",
            help="wall time of one checkpoint write (capture excluded)")
        self._c_bytes = reg.counter(
            "checkpoint_save_bytes_total",
            help="bytes written across all committed checkpoints")
        self._c_skipped = reg.counter(
            "checkpoint_save_skipped_total",
            help="async saves skipped because the writer was busy")
        self._c_failures = reg.counter(
            "checkpoint_save_failures_total",
            help="checkpoint writes that raised (checkpoint not committed)")
        self._g_last = reg.gauge(
            "checkpoint_last_step",
            help="step of the most recently committed checkpoint")
        self._c_restores = reg.counter(
            "checkpoint_restores_total", help="successful checkpoint restores")
        self._h_restore = reg.histogram(
            "checkpoint_restore_seconds",
            help="wall time of checkpoint discovery + validation + load")
        self._c_fallbacks = reg.counter(
            "checkpoint_restore_fallbacks_total",
            help="corrupt/invalid checkpoints skipped during restore "
                 "in favor of an older retained one")

    # -- save ---------------------------------------------------------------------
    def save(self, arrays: Dict[str, Dict[str, object]],
             opt_tree=None, meta: Optional[dict] = None, step: int = 0,
             blocking: bool = True) -> Optional[str]:
        """Write one checkpoint.  ``blocking=False`` enqueues to the writer
        thread and returns immediately (None; or skips if one is already in
        flight).  ``blocking=True`` writes inline and returns the committed
        path."""
        if self._closed:
            raise MXNetError("CheckpointManager is closed")
        job = (arrays, opt_tree, dict(meta or {}), int(step))
        if blocking:
            self.wait()  # an in-flight async save must not race the commit
            with _span("checkpoint.save_sync"):
                return self._write(*job, mode="sync")
        self._ensure_writer()
        with self._lock:
            if not self._idle.is_set():
                self._c_skipped.inc()
                return None
            self._idle.clear()
        # explicit trace handoff across the writer-thread boundary: the
        # async save span joins the submitting fit's trace
        from ..observability import tracing as _tracing

        self._queue.put((job, _tracing.current_trace()))
        return None

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until no async save is in flight."""
        return self._idle.wait(timeout)

    def close(self, timeout: Optional[float] = None) -> None:
        """Drain the writer and stop accepting saves."""
        self.wait(timeout)
        self._closed = True

    def _ensure_writer(self) -> None:
        if self._writer is not None and self._writer.is_alive():
            return
        t = threading.Thread(target=self._writer_loop,
                             name="tpumx-ckpt-writer", daemon=True)
        self._writer = t
        t.start()

    def _writer_loop(self) -> None:
        from ..observability import tracing as _tracing

        while True:
            job, trace_ctx = self._queue.get()
            try:
                with _tracing.use_context(trace_ctx):
                    with _span("checkpoint.save_async"):
                        self._write(*job, mode="async")
            except Exception as e:  # noqa: BLE001 — a failed save must not
                # kill the writer; the next save gets a fresh chance
                self._c_failures.inc()
                _logger.warning("async checkpoint save failed: %s", e)
            finally:
                self._idle.set()

    def _write(self, arrays, opt_tree, meta, step, mode: str) -> str:
        t0 = time.perf_counter()
        final = os.path.join(self.directory, f"ckpt-{step:010d}")
        tmp = tempfile.mkdtemp(prefix=f".tmp-ckpt-{step:010d}-",
                               dir=self.directory)
        try:
            files: Dict[str, dict] = {}
            key_lists: Dict[str, List[str]] = {}
            total_bytes = 0
            for group, kv in (arrays or {}).items():
                fname = f"{group}.npz"
                path = os.path.join(tmp, fname)
                as_np = {k: _np.asarray(v) for k, v in kv.items()}
                # np.savez mangles keys containing '/' on extraction paths;
                # param names are flat identifiers in practice, but keep
                # the authoritative list in the manifest regardless
                with open(path, "wb") as f:
                    _np.savez(f, **as_np)
                files[fname] = {"sha256": file_sha256(path),
                                "bytes": os.path.getsize(path)}
                key_lists[group] = sorted(as_np)
                total_bytes += files[fname]["bytes"]
            if opt_tree is not None:
                import jax

                host_tree = jax.tree_util.tree_map(_np.asarray, opt_tree)
                path = os.path.join(tmp, _OPT_FILE)
                with open(path, "wb") as f:
                    pickle.dump(host_tree, f, protocol=4)
                files[_OPT_FILE] = {"sha256": file_sha256(path),
                                    "bytes": os.path.getsize(path)}
                total_bytes += files[_OPT_FILE]["bytes"]
            manifest = {
                "format": 1,
                "step": step,
                "saved_unix": time.time(),
                "files": files,
                "keys": key_lists,
                "meta": _json_safe(meta),
            }
            mpath = os.path.join(tmp, _MANIFEST)
            with open(mpath, "w") as f:
                json.dump(manifest, f, indent=1, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            if os.path.exists(final):  # re-save of the same step: replace
                shutil.rmtree(final, ignore_errors=True)
            os.rename(tmp, final)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        dt = time.perf_counter() - t0
        self._h_save.observe(dt)
        self._c_bytes.inc(total_bytes)
        _registry().counter(
            "checkpoint_saves_total", labels={"mode": mode},
            help="committed checkpoints by save mode").inc()
        self._g_last.set(step)
        # fault injection (docs/fault_tolerance.md): corrupt the checkpoint
        # we JUST committed — restore must fall back to the previous one
        from ..fault import injector, corrupt_checkpoint

        cmode = injector().ckpt_corrupt_mode()
        if cmode:
            corrupt_checkpoint(final, cmode)
        self._gc()
        return final

    # -- discovery / validation ---------------------------------------------------
    def list(self) -> List[Tuple[int, str]]:
        """All committed checkpoint dirs as (step, path), newest first."""
        out = []
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return []
        for name in entries:
            m = _CKPT_RE.match(name)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.directory, name)))
        return sorted(out, reverse=True)

    def validate(self, path: str) -> Optional[dict]:
        """The checkpoint's manifest when it is fully intact, else None."""
        mpath = os.path.join(path, _MANIFEST)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None
        for fname, info in manifest.get("files", {}).items():
            fpath = os.path.join(path, fname)
            if not os.path.exists(fpath):
                return None
            if os.path.getsize(fpath) != info.get("bytes"):
                return None
            if file_sha256(fpath) != info.get("sha256"):
                return None
        return manifest

    def latest(self) -> Optional[CheckpointInfo]:
        """Newest VALID checkpoint; corrupt ones are skipped (warned +
        counted) in favor of the previous retained one."""
        for step, path in self.list():
            manifest = self.validate(path)
            if manifest is not None:
                return CheckpointInfo(path, step, manifest)
            self._c_fallbacks.inc()
            _logger.warning(
                "checkpoint %s failed validation (corrupt/truncated); "
                "falling back to the previous retained checkpoint", path)
        return None

    # -- restore ------------------------------------------------------------------
    def restore(self) -> Optional[Tuple[CheckpointInfo, Dict[str, Dict],
                                        object]]:
        """Load the newest valid checkpoint: returns ``(info, arrays,
        opt_tree)`` with arrays as ``{group: {key: np.ndarray}}``, or None
        when no valid checkpoint exists."""
        t0 = time.perf_counter()
        with _span("checkpoint.restore"):
            info = self.latest()
            if info is None:
                return None
            arrays: Dict[str, Dict[str, _np.ndarray]] = {}
            for fname in info.manifest.get("files", {}):
                if fname == _OPT_FILE or not fname.endswith(".npz"):
                    continue
                group = fname[:-len(".npz")]
                with _np.load(os.path.join(info.path, fname),
                              allow_pickle=False) as z:
                    arrays[group] = {k: z[k] for k in z.files}
                want = set(info.manifest.get("keys", {}).get(group, ()))
                have = set(arrays[group])
                missing = sorted(want - have)
                if missing:
                    raise MXNetError(
                        f"checkpoint {info.path} group {group!r} is missing "
                        f"key {missing[0]!r} despite a clean checksum")
            opt_tree = None
            opt_path = os.path.join(info.path, _OPT_FILE)
            if os.path.exists(opt_path):
                with open(opt_path, "rb") as f:
                    opt_tree = pickle.load(f)
        self._c_restores.inc()
        self._h_restore.observe(time.perf_counter() - t0)
        return info, arrays, opt_tree

    # -- retention ----------------------------------------------------------------
    def _gc(self) -> None:
        for step, path in self.list()[self.keep:]:
            shutil.rmtree(path, ignore_errors=True)
        # stale tmp dirs from a crashed writer
        now = time.time()
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return
        for name in entries:
            if name.startswith(".tmp-ckpt-"):
                path = os.path.join(self.directory, name)
                try:
                    if now - os.path.getmtime(path) > 300:
                        shutil.rmtree(path, ignore_errors=True)
                except OSError:
                    pass
