"""TrainCheckpointer: the complete donated fused-step state ↔ disk.

What one training checkpoint must carry for an *identical* resumed loss
trajectory (docs/fault_tolerance.md):

- parameters + aux states (BatchNorm running stats) — device-copied off
  the executor's donated buffers (``Executor.snapshot_arrays``; sharded
  mp leaves gather through the host so the file always holds full,
  replicated-identical arrays restorable under ANY mesh shape);
- optimizer state — the Updater's per-slot ``create_state`` pytrees,
  including AMP ``(master_f32, inner)`` master weights, device-copied the
  same way;
- the optimizer's host counters (``num_update``, per-slot update counts) —
  Adam's bias correction reads them, so dropping them would silently
  change the resumed trajectory;
- the AMP loss-scaler ``(scale, good_steps)`` state;
- the global RNG key (dropout streams resume where they left off);
- the data position: epoch, batches-completed-in-epoch, global step —
  ``Module.fit(resume=True)`` fast-forwards the iterator mid-epoch.

Capture happens on the fit thread as cheap device-side copies (the next
step's donation cannot invalidate them); the device→host transfer,
serialization and atomic commit run on the manager's writer thread.
"""
from __future__ import annotations

import logging
from typing import Dict, Optional

import numpy as _np

from ..base import MXNetError
from .manager import CheckpointManager

__all__ = ["TrainCheckpointer", "ResumePoint", "capture_train_state",
           "restore_train_state"]

_logger = logging.getLogger("mxnet_tpu.checkpoint")


class ResumePoint:
    """Where a restored run continues."""

    __slots__ = ("epoch", "nbatch", "global_step", "step")

    def __init__(self, epoch: int, nbatch: int, global_step: int):
        self.epoch = int(epoch)
        self.nbatch = int(nbatch)          # batches completed in `epoch`
        self.global_step = int(global_step)
        self.step = self.global_step

    def __repr__(self):
        return (f"ResumePoint(epoch={self.epoch}, nbatch={self.nbatch}, "
                f"global_step={self.global_step})")


def _pack_states_device(states: Dict) -> Dict:
    """Device-copy every NDArray leaf of the Updater's state structures
    (donation-safe snapshot, no host sync)."""
    import jax.numpy as jnp

    from ..ndarray.ndarray import NDArray

    def cp(s):
        if s is None:
            return None
        if isinstance(s, (tuple, list)):
            return tuple(cp(x) for x in s)
        if isinstance(s, NDArray):
            x = s._data
            if x is None:
                return None
            try:
                multi = len(x.devices()) > 1
            except Exception:
                multi = False
            # sharded/multi-device leaves gather via host (same rule as
            # Executor.snapshot_arrays); single-device leaves copy on device
            return _np.asarray(x) if multi else jnp.array(x, copy=True)
        return s
    return {int(k): cp(v) for k, v in states.items()}


def _states_from_host(tree: Dict):
    """Rebuild Updater.states NDArray structures from the pickled host
    tree (mirrors Updater.set_states' unpack)."""
    from ..ndarray import array as nd_array

    def un(s):
        if s is None:
            return None
        if isinstance(s, (tuple, list)):
            return tuple(un(x) for x in s)
        if isinstance(s, _np.ndarray):
            return nd_array(s)
        return s
    return {int(k): un(v) for k, v in tree.items()}


def capture_train_state(mod) -> tuple:
    """Snapshot a Module's full train state as ``(arrays, opt_tree, meta)``:
    device-side copies only — safe against the next step's donation, no
    host sync on the calling thread (single-device layouts)."""
    if mod._exec is None or not mod.params_initialized:
        raise MXNetError("capture_train_state: module is not "
                         "bound/initialized")
    args, aux = mod._exec.snapshot_arrays()
    param_names = set(mod._param_names)
    arrays = {"params": {k: v for k, v in args.items() if k in param_names},
              "aux": aux}
    opt_tree = None
    meta: Dict[str, object] = {}
    if getattr(mod, "_updater", None) is not None:
        opt_tree = _pack_states_device(mod._updater.states)
    if getattr(mod, "_optimizer", None) is not None:
        meta["optimizer"] = {
            "num_update": int(mod._optimizer.num_update),
            "index_update_count": {
                str(k): int(v) for k, v in
                mod._optimizer._index_update_count.items()},
        }
    if getattr(mod, "_loss_scaler", None) is not None:
        # raw device scalars: the writer thread floats them into the
        # manifest, so AMP checkpoints add no sync to the fit thread
        s = mod._loss_scaler.state()
        meta["scaler"] = [s[0], s[1]]
    from .. import random as _random

    rng = _random.get_state()
    if rng is not None:
        meta["rng"] = [int(x) for x in _np.asarray(rng).ravel()]
    return arrays, opt_tree, meta


def restore_train_state(mod, info, arrays, opt_tree) -> ResumePoint:
    """Install a restored checkpoint (from ``CheckpointManager.restore``)
    into a bound Module: params, aux, optimizer state + host counters,
    loss-scaler state, RNG.  Returns the resume point."""
    import jax.numpy as jnp

    params = arrays.get("params", {})
    missing = sorted(n for n in mod._param_names
                     if n not in params and n in (mod._exec.arg_dict or {}))
    if missing:
        raise MXNetError(
            f"checkpoint {info.path} is missing parameter {missing[0]!r} "
            f"required by the bound symbol ({len(missing)} missing in "
            "total)")
    for n, v in params.items():
        dst = mod._exec.arg_dict.get(n)
        if dst is None:
            continue
        if tuple(dst.shape) != tuple(v.shape):
            raise MXNetError(
                f"checkpoint {info.path}: parameter {n!r} has shape "
                f"{tuple(v.shape)}, bound symbol expects "
                f"{tuple(dst.shape)}")
        dst._data = jnp.asarray(v, dtype=dst._data.dtype)
    for n, v in arrays.get("aux", {}).items():
        dst = mod._exec.aux_dict.get(n)
        if dst is not None:
            dst._data = jnp.asarray(v, dtype=dst._data.dtype)
    if getattr(mod, "_sync_params_from_exec", None) is not None:
        mod._sync_params_from_exec()
    if opt_tree is not None and getattr(mod, "_updater", None) is not None:
        mod._updater.states = _states_from_host(opt_tree)
    meta = info.meta
    opt_meta = meta.get("optimizer")
    if opt_meta and getattr(mod, "_optimizer", None) is not None:
        mod._optimizer.num_update = int(opt_meta["num_update"])
        mod._optimizer._index_update_count = {
            int(k): int(v)
            for k, v in opt_meta["index_update_count"].items()}
    if meta.get("scaler") is not None \
            and getattr(mod, "_loss_scaler", None) is not None:
        s = meta["scaler"]
        mod._loss_scaler.set_state((jnp.float32(s[0]), jnp.float32(s[1])))
    if meta.get("rng") is not None:
        from .. import random as _random

        _random.set_state(_np.asarray(meta["rng"], dtype=_np.uint32))
    return ResumePoint(meta.get("epoch", 0), meta.get("nbatch", 0),
                       meta.get("global_step", info.step))


class TrainCheckpointer:
    """Periodic async + final synchronous checkpoints for ``Module.fit``.

    ``every``: global-step cadence of async saves (0 = only preemption
    saves).  ``keep``: retained checkpoint count.  The module must be
    bound with initialized params and optimizer before ``capture``/
    ``restore`` (fit guarantees this).
    """

    def __init__(self, module, directory: str, every: int = 0,
                 keep: int = 3):
        if not (hasattr(module, "_exec") and hasattr(module, "_updater")):
            raise MXNetError(
                "TrainCheckpointer needs a Module-like with a bound "
                f"executor and updater; got {type(module).__name__}")
        self.module = module
        self.manager = CheckpointManager(directory, keep=keep)
        self.every = int(every or 0)
        self._preempt = None

    # -- preemption wiring --------------------------------------------------------
    def attach_preemption(self, handler) -> None:
        self._preempt = handler

    # -- capture ------------------------------------------------------------------
    def capture(self) -> tuple:
        return capture_train_state(self.module)

    # -- save ---------------------------------------------------------------------
    def save(self, epoch: int, nbatch: int, global_step: int,
             blocking: bool = False) -> None:
        arrays, opt_tree, meta = self.capture()
        meta.update({"epoch": int(epoch), "nbatch": int(nbatch),
                     "global_step": int(global_step)})
        self.manager.save(arrays, opt_tree, meta, step=int(global_step),
                          blocking=blocking)

    def after_batch(self, epoch: int, nbatch: int,
                    global_step: int) -> bool:
        """fit's per-batch hook.  Returns True when a preemption fired: the
        final checkpoint has been written SYNCHRONOUSLY and fit must exit
        gracefully."""
        if self._preempt is not None and self._preempt.poll(global_step):
            _logger.info(
                "preemption signal at epoch %d batch %d (step %d): writing "
                "final synchronous checkpoint", epoch, nbatch, global_step)
            self.save(epoch, nbatch, global_step, blocking=True)
            return True
        if self.every and global_step % self.every == 0:
            self.save(epoch, nbatch, global_step, blocking=False)
        return False

    def close(self) -> None:
        self.manager.close()

    # -- restore ------------------------------------------------------------------
    def restore(self) -> Optional[ResumePoint]:
        """Load the newest VALID checkpoint into the module (params, aux,
        optimizer state + counters, scaler, RNG) and return the resume
        point, or None when the directory holds no valid checkpoint."""
        res = self.manager.restore()
        if res is None:
            return None
        info, arrays, opt_tree = res
        return restore_train_state(self.module, info, arrays, opt_tree)
