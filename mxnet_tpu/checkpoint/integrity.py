"""Checkpoint file integrity: checksums + manifest validation.

Shared by the async checkpoint manager (per-file sha256 in every
checkpoint manifest) and the classic ``save_checkpoint``/``load_checkpoint``
prefix-epoch format (a sidecar ``<file>.manifest.json``), so a truncated
or bit-flipped checkpoint is detected BEFORE deserialization and surfaces
as a clear :class:`MXNetError` naming the file and the failing key —
never a cryptic unpickling/struct error deep in a load.
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional

from ..base import MXNetError

__all__ = ["file_sha256", "write_params_manifest", "verify_params_file",
           "manifest_path_for"]

_CHUNK = 1 << 20


def file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_CHUNK)
            if not chunk:
                break
            h.update(chunk)
    return h.hexdigest()


def manifest_path_for(params_path: str) -> str:
    return params_path + ".manifest.json"


def write_params_manifest(params_path: str, keys: List[str]) -> str:
    """Write the sidecar manifest for a params file: its sha256 + the full
    key list (param-manifest completeness check on load)."""
    manifest = {
        "format": 1,
        "file": os.path.basename(params_path),
        "bytes": os.path.getsize(params_path),
        "sha256": file_sha256(params_path),
        "keys": sorted(keys),
    }
    path = manifest_path_for(params_path)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def verify_params_file(params_path: str,
                       loaded_keys: Optional[List[str]] = None) -> Optional[Dict]:
    """Validate a params file against its sidecar manifest (when present).

    Call once BEFORE loading (``loaded_keys=None``: existence + size +
    checksum) and once after (``loaded_keys=[...]``: manifest completeness —
    every manifest key must have been loaded).  Raises :class:`MXNetError`
    naming the file / the missing key; returns the manifest dict, or None
    when no manifest exists (legacy checkpoints stay loadable).
    """
    if not os.path.exists(params_path):
        raise MXNetError(f"checkpoint file {params_path!r} does not exist")
    mpath = manifest_path_for(params_path)
    if not os.path.exists(mpath):
        return None
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (json.JSONDecodeError, OSError) as e:
        raise MXNetError(
            f"checkpoint manifest {mpath!r} is unreadable/corrupt: {e}")
    if loaded_keys is None:
        size = os.path.getsize(params_path)
        if "bytes" in manifest and size != manifest["bytes"]:
            raise MXNetError(
                f"checkpoint file {params_path!r} is truncated/corrupt: "
                f"{size} bytes on disk, manifest expects "
                f"{manifest['bytes']}")
        if "sha256" in manifest:
            digest = file_sha256(params_path)
            if digest != manifest["sha256"]:
                raise MXNetError(
                    f"checkpoint file {params_path!r} failed its checksum "
                    f"(sha256 {digest[:12]}… != manifest "
                    f"{manifest['sha256'][:12]}…): the file is corrupt")
    else:
        missing = sorted(set(manifest.get("keys", ())) - set(loaded_keys))
        if missing:
            raise MXNetError(
                f"checkpoint file {params_path!r} is incomplete: manifest "
                f"key {missing[0]!r} is missing from the loaded parameters "
                f"({len(missing)} missing in total)")
    return manifest
