"""mxnet_tpu.checkpoint — async distributed checkpointing (ROADMAP item 3's
production half; docs/fault_tolerance.md).

- :class:`CheckpointManager` — atomic write-to-temp-then-rename checkpoint
  directories with per-file sha256 checksums, a background writer thread
  (the train step never stalls on host transfer or file IO), retention of
  the last K checkpoints, and restore that skips corrupt/truncated
  checkpoints in favor of the previous retained one.
- :class:`TrainCheckpointer` — the ``Module.fit`` bridge: captures the
  COMPLETE donated fused-step state (params, optimizer state incl. AMP f32
  masters, loss scaler, RNG, iterator position, step counters) as
  device-side copies and restores it under any mesh shape.
- :mod:`.integrity` — checksum + manifest validation shared with the
  classic ``save_checkpoint``/``load_checkpoint`` prefix-epoch format.

``Module.fit(checkpoint_dir=..., checkpoint_every=N, resume=True)`` is the
one-line spelling; SIGTERM/SIGINT mid-fit triggers a final synchronous
checkpoint and a graceful exit (mxnet_tpu.fault.preemption).
"""
from __future__ import annotations

from .integrity import (file_sha256, manifest_path_for, verify_params_file,
                        write_params_manifest)
from .manager import CheckpointInfo, CheckpointManager
from .train_state import (ResumePoint, TrainCheckpointer,
                          capture_train_state, restore_train_state)
from . import integrity
from . import manager
from . import train_state

__all__ = ["CheckpointManager", "CheckpointInfo", "TrainCheckpointer",
           "ResumePoint", "capture_train_state", "restore_train_state",
           "file_sha256", "write_params_manifest", "verify_params_file",
           "manifest_path_for", "integrity", "manager", "train_state"]
