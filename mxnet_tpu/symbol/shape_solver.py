"""Shape inference for symbol graphs.

Reference: NNVM bidirectional ``InferShape`` pass
(``src/executor/infer_graph_attr_pass.cc``).  TPU-native version: output
shapes come from ``jax.eval_shape`` over each op's emitter (no duplicated
shape logic), and *parameter* shapes (weight/bias/gamma/...) are solved
forward from data shapes + op attrs via per-op rules — the only place shape
knowledge is written twice, and only for the seven param-taking op families.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as _np
import jax

from ..base import MXNetError
from .graph import attr_bool, Node, SymbolEntry, _active_extra_inputs, input_nodes, topo_order

def _param_shape_rule(op_name: str, slot: str, attrs: dict,
                      in_shapes: List[Tuple[int, ...]]) -> Tuple[int, ...]:
    """Shape of a learnable/aux input given the data input shapes."""
    data = in_shapes[0]
    if op_name in ("_tpumx_quantized_fc_int8", "_tpumx_quantized_conv_int8"):
        # int8 twins (docs/quantization.md): data_q mirrors the float data
        # shape, weight follows the float op's rule, wscale/bias are
        # per-output-channel, act_scale is the quantize node's (1,) output
        if slot == "act_scale":
            return (1,)
        base = ("FullyConnected" if op_name == "_tpumx_quantized_fc_int8"
                else "Convolution")
        if slot == "weight":
            return _param_shape_rule(base, "weight", attrs, in_shapes)
        return _param_shape_rule(base, "bias", attrs, in_shapes)
    if op_name == "FullyConnected":
        nh = int(attrs["num_hidden"])
        flat = 1
        if attr_bool(attrs.get("flatten"), default=True):
            for d in data[1:]:
                flat *= d
        else:
            flat = data[-1]
        return (nh, flat) if slot == "weight" else (nh,)
    if op_name == "Convolution":
        from ..ops.nn import is_channels_last

        nf = int(attrs["num_filter"])
        kernel = tuple(int(k) for k in attrs["kernel"])
        ng = int(attrs.get("num_group", 1))
        cin = data[-1] if is_channels_last(attrs.get("layout")) else data[1]
        if slot == "weight":
            # channels-last convs take the reference's O<spatial>I weights
            if is_channels_last(attrs.get("layout")):
                return (nf,) + kernel + (cin // ng,)
            return (nf, cin // ng) + kernel
        return (nf,)
    if op_name == "Deconvolution":
        nf = int(attrs["num_filter"])
        kernel = tuple(int(k) for k in attrs["kernel"])
        ng = int(attrs.get("num_group", 1))
        cin = data[1]
        if slot == "weight":
            # reference layout: (in_channels, num_filter/num_group, *kernel)
            return (cin, nf // ng) + kernel
        return (nf,)
    if op_name in ("BatchNorm", "InstanceNorm"):
        ax = int(attrs.get("axis", 1))
        return (data[ax],)
    if op_name == "LayerNorm":
        ax = int(attrs.get("axis", -1))
        return (data[ax],)
    if op_name == "Embedding":
        return (int(attrs["input_dim"]), int(attrs["output_dim"]))
    if op_name == "LeakyReLU":
        return (data[1],)
    if op_name == "RNN":
        from ..ops.rnn import rnn_param_size

        H = int(attrs["state_size"])
        L = int(attrs["num_layers"])
        bi = bool(attrs.get("bidirectional", False))
        dirs = 2 if bi else 1
        T, N, I = data
        if slot == "parameters":
            return (rnn_param_size(attrs.get("mode", "lstm"), L, I, H, bi),)
        return (L * dirs, N, H)
    raise MXNetError(f"no shape rule for {op_name}.{slot}")


def _label_shape(op_name: str, attrs: dict,
                 data: Tuple[int, ...]) -> Tuple[int, ...]:
    """Label shape of a loss-head op from its data shape (the reference's
    FInferShape for these ops runs backward from data, so binding without
    label shapes works — e.g. Module.bind(for_training=False))."""
    if op_name in ("SoftmaxOutput", "Softmax"):
        if attr_bool(attrs.get("multi_output")):
            return (data[0],) + tuple(data[2:])
        if attr_bool(attrs.get("preserve_shape")):
            return tuple(data[:-1])
        return (data[0],)
    if op_name == "SVMOutput":
        return (data[0],)
    # regression heads: label congruent with data
    return tuple(data)


_LABEL_OPS = ("SoftmaxOutput", "Softmax", "SVMOutput",
              "LinearRegressionOutput", "MAERegressionOutput",
              "LogisticRegressionOutput")


def _invert_data_shape(op_name: str, attrs: dict, partial: Tuple[int, ...],
                       param_shapes: Dict[str, Tuple[int, ...]]):
    """Fill 0 (= unknown, reference 1.x convention) dims of a data input
    from already-known parameter shapes — the contained slice of NNVM's
    bidirectional InferShape (reference
    src/executor/infer_graph_attr_pass.cc) that covers the common case:
    a known weight pins the data's feature/channel dimension."""
    out = list(partial)
    w = param_shapes.get("weight")
    if w is None or len(w) < 2:
        # a malformed/rank-deficient weight never back-fills; the forward
        # rule or eval_shape will report it with a proper MXNetError
        return tuple(out)
    if op_name == "FullyConnected":
        if attr_bool(attrs.get("flatten"), default=True):
            if len(out) == 2 and out[1] == 0:
                out[1] = w[1]
        elif out and out[-1] == 0:
            out[-1] = w[1]
    elif op_name == "Convolution":
        from ..ops.nn import is_channels_last

        ng = int(attrs.get("num_group", 1))
        if is_channels_last(attrs.get("layout")):
            if out and out[-1] == 0:
                out[-1] = w[-1] * ng
        elif len(out) > 1 and out[1] == 0:
            out[1] = w[1] * ng
    return tuple(out)


def solve_shapes(symbol, known: Dict[str, Tuple[int, ...]],
                 partial: bool = False):
    """Returns (arg_shapes, out_shapes, aux_shapes) in listing order.

    A dim of 0 in a caller-supplied shape means UNKNOWN (reference 1.x
    convention) — the solver back-fills it from known parameter shapes
    where an inverse rule exists.  With ``partial=True`` unknown inputs
    skip their consuming ops instead of raising, and unresolved entries
    come back as None (reference: infer_shape_partial)."""
    from ..ndarray.ndarray import _op_accepts_training

    entries = symbol._entries
    shapes: Dict[int, Tuple] = {}  # id(node) -> tuple of output shapes
    var_shape: Dict[str, Tuple[int, ...]] = dict(known)

    def _complete(sh) -> bool:
        return sh is not None and all(d > 0 for d in sh)

    def _deref(e: SymbolEntry) -> SymbolEntry:
        """See through AMP cast nodes (amp.convert_symbol): a cast is
        shape-transparent, and the param/label shape rules must reach the
        underlying VARIABLE (e.g. FullyConnected's weight) through it."""
        while e.node.kind == "op" and e.node.op.name == "amp_cast":
            e = e.node.inputs[0]
        return e

    for node in topo_order(entries):
        if node.kind == "op" and node.op.name == "amp_cast":
            # transparent for shapes; may be deferred (its var input gets
            # its shape from the consuming op's param rule, below)
            src = _deref(node.inputs[0])
            if id(src.node) in shapes:
                shapes[id(node)] = (shapes[id(src.node)][src.index],)
            continue
        if node.kind == "var":
            if _complete(var_shape.get(node.name)):
                shapes[id(node)] = (tuple(var_shape[node.name]),)
            elif node.attr_dict.get("__shape__"):
                sh = tuple(eval(node.attr_dict["__shape__"]))  # noqa: S307 — own format
                if node.name not in var_shape:
                    var_shape[node.name] = sh
                # a declared shape with 0-dims stays deferred so backward
                # inference can fill it, same as caller-supplied partials
                if _complete(var_shape[node.name]):
                    shapes[id(node)] = (tuple(var_shape[node.name]),)
            # else: deferred — a consuming op's rule will fill it (param
            # rule forward, or _invert_data_shape backward from a weight)
            continue
        op = node.op
        params, aux = _active_extra_inputs(op.name, node.attrs)
        extra = list(params) + list(aux)
        n_data = len(node.inputs) - len(extra)
        in_shapes: List[Tuple[int, ...]] = []
        unknown_input = False
        # data inputs must be known — except a loss head's label variable
        # (inferred from the data shape like the reference) and a var with
        # 0-dims a known weight can pin (backward inference)
        for i, e in enumerate(node.inputs[:n_data]):
            e = _deref(e)
            if id(e.node) not in shapes:
                if (i == n_data - 1 and op.name in _LABEL_OPS
                        and e.node.kind == "var" and in_shapes):
                    sh = _label_shape(op.name, node.attrs, in_shapes[0])
                    var_shape[e.node.name] = sh
                    shapes[id(e.node)] = (sh,)
                    in_shapes.append(sh)
                    continue
                if e.node.kind == "var" and e.node.name in var_shape:
                    pshapes = {
                        slot: var_shape[pe.node.name]
                        for slot, pe in ((s, _deref(p)) for s, p in
                                         zip(extra, node.inputs[n_data:]))
                        if pe.node.kind == "var"
                        and _complete(var_shape.get(pe.node.name))}
                    cand = _invert_data_shape(op.name, node.attrs,
                                              var_shape[e.node.name], pshapes)
                    if _complete(cand):
                        var_shape[e.node.name] = cand
                        shapes[id(e.node)] = (cand,)
                        in_shapes.append(cand)
                        continue
                if partial:
                    unknown_input = True
                    break
                raise MXNetError(
                    f"infer_shape: input {e.node.name!r} of op {node.name!r} has unknown shape")
            in_shapes.append(shapes[id(e.node)][e.index])
        if unknown_input:
            continue  # partial mode: this op's outputs stay unknown
        # solve param/aux shapes; caller-GIVEN shapes (complete or partial)
        # are validated against the op rule — a typo'd weight must raise,
        # not silently build a wrong-sized model
        for slot, e in zip(extra, node.inputs[n_data:]):
            e = _deref(e)
            given = var_shape.get(e.node.name) \
                if e.node.kind == "var" else None
            if id(e.node) in shapes and given is None:
                in_shapes.append(shapes[id(e.node)][e.index])
                continue
            try:
                sh = _param_shape_rule(op.name, slot, node.attrs, in_shapes)
            except MXNetError:
                if id(e.node) in shapes:  # no rule, but shape known: accept
                    in_shapes.append(shapes[id(e.node)][e.index])
                    continue
                raise
            if given is not None and (
                    len(given) != len(sh)
                    or any(g not in (0, s) for g, s in zip(given, sh))):
                raise MXNetError(
                    f"infer_shape: {e.node.name!r} given as {tuple(given)} "
                    f"but op {node.name!r} requires {sh}")
            var_shape[e.node.name] = sh
            shapes[id(e.node)] = (sh,)
            in_shapes.append(sh)
        # abstract-eval the op for output shapes
        kwargs = dict(node.attrs)
        if _op_accepts_training(op):
            kwargs["_training"] = False
        structs = [jax.ShapeDtypeStruct(s, _np.float32) for s in in_shapes]
        try:
            if op.rng:
                out = jax.eval_shape(lambda *a: op.fn(*a, rng_key=jax.random.PRNGKey(0), **kwargs), *structs)
            else:
                out = jax.eval_shape(lambda *a: op.fn(*a, **kwargs), *structs)
        except Exception as e:
            raise MXNetError(f"infer_shape failed at op {node.name!r} ({op.name}): {e}") from e
        outs = out if isinstance(out, (tuple, list)) else (out,)
        shapes[id(node)] = tuple(tuple(o.shape) for o in outs)

    arg_shapes = []
    for n in input_nodes(entries):
        if n.attr_dict.get("__is_aux__"):
            continue
        if not _complete(var_shape.get(n.name)):
            if partial:
                arg_shapes.append(None)
                continue
            raise MXNetError(f"infer_shape: could not determine shape of {n.name!r}")
        arg_shapes.append(tuple(var_shape[n.name]))
    aux_shapes = []
    for n in input_nodes(entries):
        if not n.attr_dict.get("__is_aux__"):
            continue
        if not _complete(var_shape.get(n.name)):
            if partial:
                aux_shapes.append(None)
                continue
            raise MXNetError(f"infer_shape: could not determine shape of {n.name!r}")
        aux_shapes.append(tuple(var_shape[n.name]))
    out_shapes = []
    for e in entries:
        e = _deref(e)
        if id(e.node) in shapes:
            out_shapes.append(shapes[id(e.node)][e.index])
        elif partial:
            out_shapes.append(None)
        else:
            raise MXNetError(
                f"infer_shape: could not determine shape of output "
                f"{e.node.name!r}")
    return arg_shapes, out_shapes, aux_shapes
