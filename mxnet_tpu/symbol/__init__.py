"""`mx.sym` namespace (reference: python/mxnet/symbol/)."""
from __future__ import annotations

import sys

from ..ops.registry import OP_REGISTRY
from .symbol import (Symbol, Variable, var, Group, load, load_json, zeros, ones,
                     _make_sym_wrapper)
from . import graph  # noqa: F401

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json"]

_mod = sys.modules[__name__]
for _name in list(OP_REGISTRY):
    if not hasattr(_mod, _name):
        setattr(_mod, _name, _make_sym_wrapper(_name))

# random sub-namespace (reference: symbol/random.py — sym.random.uniform
# et al. map to the _random_* ops)
class _SymRandom:
    pass


random = _SymRandom()
for _name in list(OP_REGISTRY):
    if _name.startswith("_random_"):
        setattr(random, _name[len("_random_"):], getattr(_mod, _name))
# sampling ops the reference exposes under sym.random beyond _random_*
random.multinomial = getattr(_mod, "multinomial")
random.shuffle = getattr(_mod, "shuffle")


# contrib sub-namespace
class _Contrib:
    pass


contrib = _Contrib()
for _name in list(OP_REGISTRY):
    if _name.startswith("_contrib_"):
        setattr(contrib, _name[len("_contrib_"):], getattr(_mod, _name))
        setattr(contrib, _name, getattr(_mod, _name))

# traceable control flow (reference: src/operator/control_flow.cc via
# python/mxnet/symbol/contrib.py)
from .control_flow import foreach, while_loop, cond  # noqa: E402

contrib.foreach = foreach
contrib.while_loop = while_loop
contrib.cond = cond


def __getattr__(name):
    """Late-registered ops (e.g. 'Custom', registered by mx.operator at
    import) get wrappers on demand."""
    if name in OP_REGISTRY:
        wrapper = _make_sym_wrapper(name)
        setattr(_mod, name, wrapper)
        return wrapper
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
