"""Symbol: the symbolic expression frontend.

Reference: ``python/mxnet/symbol/symbol.py`` (compose/infer/bind — simple_bind
:1288, bind :1552) over NNVM.  Here a Symbol is a list of (node, index)
entries; binding traces the DAG to a pure JAX function compiled as one HLO
module (the reference's per-node engine pushes + op bulking taken to the
whole-graph limit — SURVEY.md §7 step 3).
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

import numpy as _np
import jax
import jax.numpy as jnp

from ..base import MXNetError, np_dtype
from ..context import Context, current_context
from ..ops.registry import Op, OP_REGISTRY, get_op
from .. import attribute, name as _name_mod
from .graph import Node, SymbolEntry, OP_EXTRA_INPUTS, _active_extra_inputs, \
    input_nodes, topo_order, trace

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json", "zeros", "ones"]


class Symbol:
    __slots__ = ("_entries",)

    def __init__(self, entries: Sequence[SymbolEntry]):
        self._entries = list(entries)

    # -- identity -----------------------------------------------------------------
    @property
    def name(self) -> Optional[str]:
        if len(self._entries) == 1:
            return self._entries[0].node.name
        return None

    def __repr__(self):
        outs = ", ".join(self.list_outputs())
        return f"<Symbol {outs}>"

    def __iter__(self):
        for i in range(len(self._entries)):
            yield Symbol([self._entries[i]])

    def __len__(self):
        return len(self._entries)

    def __getitem__(self, index):
        if isinstance(index, str):
            outs = self.list_outputs()
            if index not in outs:
                raise ValueError(f"no output named {index!r}; have {outs}")
            return Symbol([self._entries[outs.index(index)]])
        if isinstance(index, slice):
            return Symbol(self._entries[index])
        return Symbol([self._entries[index]])

    # -- listing ------------------------------------------------------------------
    def list_arguments(self) -> List[str]:
        return [n.name for n in input_nodes(self._entries)
                if not n.attr_dict.get("__is_aux__")]

    def list_outputs(self) -> List[str]:
        outs = []
        for e in self._entries:
            base = e.node.name
            if e.node.kind == "op" and e.node.num_outputs() > 1:
                outs.append(f"{base}_output{e.index}")
            elif e.node.kind == "op":
                outs.append(f"{base}_output")
            else:
                outs.append(base)
        return outs

    def list_auxiliary_states(self) -> List[str]:
        return [n.name for n in input_nodes(self._entries)
                if n.attr_dict.get("__is_aux__")]

    def list_inputs(self) -> List[str]:
        return [n.name for n in input_nodes(self._entries)]

    def get_internals(self) -> "Symbol":
        entries = []
        for n in topo_order(self._entries):
            for i in range(n.num_outputs()):
                entries.append(SymbolEntry(n, i))
        return Symbol(entries)

    def get_children(self) -> Optional["Symbol"]:
        node = self._entries[0].node
        if not node.inputs:
            return None
        return Symbol(list(node.inputs))

    def attr(self, key):
        return self._entries[0].node.attr_dict.get(key)

    def list_attr(self):
        return dict(self._entries[0].node.attr_dict)

    def attr_dict(self):
        out = {}
        for n in topo_order(self._entries):
            if n.attr_dict:
                out[n.name] = dict(n.attr_dict)
        return out

    def _set_attr(self, **kwargs):
        for e in self._entries:
            e.node.attr_dict.update({k: str(v) for k, v in kwargs.items()})

    # -- composition --------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        """Compose: replace free variables with provided symbols."""
        s = self.__copy__()
        s._compose(*args, **kwargs)
        return s

    def __copy__(self):
        # deep-copy the reachable subgraph
        mapping: Dict[int, Node] = {}

        def copy_node(n: Node) -> Node:
            if id(n) in mapping:
                return mapping[id(n)]
            nn = Node(n.kind, n.name, n.op, dict(n.attrs),
                      [SymbolEntry(copy_node(e.node), e.index) for e in n.inputs],
                      dict(n.attr_dict))
            mapping[id(n)] = nn
            return nn

        return Symbol([SymbolEntry(copy_node(e.node), e.index) for e in self._entries])

    def _compose(self, *args, **kwargs):
        arg_names = self.list_arguments()
        repl: Dict[str, SymbolEntry] = {}
        for i, a in enumerate(args):
            repl[arg_names[i]] = a._entries[0]
        for k, v in kwargs.items():
            if k not in arg_names:
                # silent no-op on a typo'd name leaves the free variable in
                # the graph; the reference's SymbolCompose raises
                raise MXNetError(
                    f"compose: {k!r} is not an argument of this symbol "
                    f"(arguments: {arg_names})")
            repl[k] = v._entries[0]
        for n in topo_order(self._entries):
            n.inputs = [repl[e.node.name] if (e.node.kind == "var" and e.node.name in repl)
                        else e for e in n.inputs]

    # -- arithmetic ---------------------------------------------------------------
    def _binary(self, opname, other, reverse=False):
        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return _apply_op(get_op("broadcast_" + opname), [a, b], {}, None)
        scalar = float(other)
        if reverse and opname in ("sub", "div", "power", "mod"):
            return _apply_op(get_op(f"_r{opname}_scalar"), [self], {"scalar": scalar}, None)
        return _apply_op(get_op(f"_{opname}_scalar"), [self], {"scalar": scalar}, None)

    def __add__(self, other):
        return self._binary("add", other)

    __radd__ = __add__

    def __sub__(self, other):
        return self._binary("sub", other)

    def __rsub__(self, other):
        return self._binary("sub", other, reverse=True)

    def __mul__(self, other):
        return self._binary("mul", other)

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binary("div", other)

    def __rtruediv__(self, other):
        return self._binary("div", other, reverse=True)

    def __pow__(self, other):
        return self._binary("power", other)

    def __neg__(self):
        return _apply_op(get_op("negative"), [self], {}, None)

    def __eq__(self, other):
        if isinstance(other, (Symbol, int, float)):
            return self._binary("equal", other)
        return NotImplemented

    def __ne__(self, other):
        if isinstance(other, (Symbol, int, float)):
            return self._binary("not_equal", other)
        return NotImplemented

    def __gt__(self, other):
        return self._binary("greater", other)

    def __ge__(self, other):
        return self._binary("greater_equal", other)

    def __lt__(self, other):
        return self._binary("lesser", other)

    def __le__(self, other):
        return self._binary("lesser_equal", other)

    def __hash__(self):
        return id(self)

    # method sugar shared with NDArray
    def reshape(self, *shape, **kw):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        if "shape" in kw:
            shape = kw["shape"]
        return _apply_op(get_op("reshape"), [self], {"shape": tuple(shape)}, None)

    def transpose(self, axes=None):
        return _apply_op(get_op("transpose"), [self], {"axes": axes or ()}, None)

    def flatten(self):
        return _apply_op(get_op("flatten"), [self], {}, None)

    def sum(self, axis=None, keepdims=False):
        return _apply_op(get_op("sum"), [self], {"axis": axis, "keepdims": keepdims}, None)

    def mean(self, axis=None, keepdims=False):
        return _apply_op(get_op("mean"), [self], {"axis": axis, "keepdims": keepdims}, None)

    def astype(self, dtype):
        return _apply_op(get_op("cast"), [self], {"dtype": np_dtype(dtype).name}, None)

    def slice_axis(self, axis, begin, end):
        return _apply_op(get_op("slice_axis"), [self],
                         {"axis": axis, "begin": begin, "end": end}, None)

    def expand_dims(self, axis):
        return _apply_op(get_op("expand_dims"), [self], {"axis": axis}, None)

    def softmax(self, axis=-1):
        return _apply_op(get_op("softmax"), [self], {"axis": axis}, None)

    # -- shape/type inference -----------------------------------------------------
    def _dummy_env(self, arg_shapes: Dict[str, tuple], arg_dtypes=None):
        env = {}
        for n in input_nodes(self._entries):
            if n.name not in arg_shapes:
                raise MXNetError(f"infer_shape: missing shape for {n.name}")
            dt = (arg_dtypes or {}).get(n.name, _np.float32)
            env[n.name] = jax.ShapeDtypeStruct(tuple(arg_shapes[n.name]), np_dtype(dt))
        return env

    def infer_shape(self, *args, **kwargs):
        """Returns (arg_shapes, out_shapes, aux_shapes) like the reference.

        Output shapes come from abstract evaluation; parameter shapes are
        solved forward from data shapes via per-op rules, and a dim given
        as 0 (= unknown, reference 1.x convention) is back-filled from
        known weight shapes where an inverse rule exists — the common slice
        of NNVM's bidirectional pass (see ``shape_solver``).
        """
        from .shape_solver import solve_shapes

        return solve_shapes(self, self._known_shapes(args, kwargs))

    def _known_shapes(self, args, kwargs) -> Dict[str, tuple]:
        known: Dict[str, tuple] = {}
        if args:
            for name, sh in zip(self.list_arguments(), args):
                if sh is not None:
                    known[name] = tuple(sh)
        known.update({k: tuple(v) for k, v in kwargs.items()})
        return known

    def infer_shape_partial(self, *args, **kwargs):
        """Like infer_shape but never raises on missing information: ops
        whose inputs are unknown are skipped and the corresponding entries
        come back as None (reference: symbol.py infer_shape_partial)."""
        from .shape_solver import solve_shapes

        return solve_shapes(self, self._known_shapes(args, kwargs),
                            partial=True)

    def infer_type(self, *args, **kwargs):
        """Propagate dtypes through the graph (reference: InferType pass).

        Rules: `cast` produces its dtype attr; comparisons keep the input
        dtype; arg-index producers report float32 (reference convention);
        everything else takes its first input's dtype."""
        from .graph import topo_order as _topo

        default = _np.float32
        var_t: Dict[str, _np.dtype] = {}
        arg_names = self.list_arguments()
        for name, a in zip(arg_names, args):
            if a is not None:
                var_t[name] = np_dtype(a)
        for k, v in kwargs.items():
            var_t[k] = np_dtype(v)
        node_t: Dict[int, _np.dtype] = {}
        for n in _topo(self._entries):
            if n.kind == "var":
                var_t.setdefault(n.name, default)
                node_t[id(n)] = var_t[n.name]
                continue
            in_ts = [node_t[id(e.node)] for e in n.inputs]
            opn = n.op.name
            if opn in ("cast", "Cast", "amp_cast"):
                t = np_dtype(n.attrs.get("dtype", "float32"))
            elif opn in ("argmax", "argmin", "argsort", "topk", "one_hot"):
                t = _np.dtype(_np.float32)
            else:
                t = in_ts[0] if in_ts else default
            node_t[id(n)] = t
        return ([var_t.get(nm, default) for nm in arg_names],
                [node_t[id(e.node)] for e in self._entries],
                [var_t.get(nm, default)
                 for nm in self.list_auxiliary_states()])

    # -- binding ------------------------------------------------------------------
    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    stype_dict=None, group2ctx=None, shared_arg_names=None,
                    shared_exec=None, shared_buffer=None, **kwargs):
        from ..executor import Executor

        ctx = ctx or current_context()
        arg_shapes, out_shapes, aux_shapes = self.infer_shape(**kwargs)
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        type_dict = type_dict or {}
        from ..ndarray import zeros

        args = {}
        for n, sh in zip(arg_names, arg_shapes):
            args[n] = zeros(sh, ctx=ctx, dtype=type_dict.get(n, "float32"))
        grad_arrays = {}
        req = grad_req if isinstance(grad_req, dict) else {n: grad_req for n in arg_names}
        for n, sh in zip(arg_names, arg_shapes):
            if req.get(n, "null") != "null":
                grad_arrays[n] = zeros(sh, ctx=ctx, dtype=type_dict.get(n, "float32"))
        aux = {n: zeros(sh, ctx=ctx) for n, sh in zip(aux_names, aux_shapes)}
        return Executor(self, ctx, args, grad_arrays, req, aux,
                        group2ctx=group2ctx)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from ..executor import Executor

        ctx = ctx or current_context()
        arg_names = self.list_arguments()
        if isinstance(args, (list, tuple)):
            args = dict(zip(arg_names, args))
        if isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(arg_names, args_grad))
        aux_names = self.list_auxiliary_states()
        if isinstance(aux_states, (list, tuple)):
            aux_states = dict(zip(aux_names, aux_states))
        req = grad_req if isinstance(grad_req, dict) else {n: grad_req for n in arg_names}
        if isinstance(grad_req, (list, tuple)):
            req = dict(zip(arg_names, grad_req))
        return Executor(self, ctx, dict(args), dict(args_grad or {}), req,
                        dict(aux_states or {}), group2ctx=group2ctx)

    def eval(self, ctx=None, **kwargs):
        ex = self.bind(ctx=ctx, args=kwargs, args_grad=None, grad_req="null")
        return ex.forward(is_train=False)

    # gradient of this symbol's (summed) outputs — reference: Symbol.grad
    def grad(self, wrt: Sequence[str]) -> "Symbol":
        """Gradient symbol of the summed outputs w.r.t. ``wrt`` arguments
        (reference: Symbol.grad / nnvm Gradient pass).  The returned symbol
        has one output per name in ``wrt`` and the same arguments as self;
        binding it evaluates the vjp with ones-seeded heads — the same
        seeding Executor.backward uses without explicit out_grads."""
        wrt = list(wrt)
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        unknown = [w for w in wrt if w not in arg_names]
        if unknown:
            raise MXNetError(f"grad: unknown argument(s) {unknown}; "
                             f"arguments are {arg_names}")
        entries = self._entries
        in_names = arg_names + aux_names
        in_syms = []
        by_name = {n.name: n for n in input_nodes(entries)}
        for n in in_names:
            in_syms.append(Symbol([SymbolEntry(by_name[n])]))

        def _grad_fn(*arrays, _training=True, rng_key=None):
            env = dict(zip(in_names, arrays))

            def f(wvals):
                e2 = dict(env)
                e2.update(wvals)
                outs = trace(entries, e2, _training, rng_key)
                return sum(jnp.sum(o.astype(jnp.float32)) for o in outs)

            _, vjp = jax.vjp(f, {n: env[n] for n in wrt})
            (g,) = vjp(jnp.ones((), jnp.float32))
            out = tuple(g[n] for n in wrt)
            return out if len(out) > 1 else out[0]

        op = Op("_grad", _grad_fn, num_outputs=len(wrt), rng=True)
        return _apply_op(op, in_syms, {},
                         (self.name or "sym") + "_grad")

    # -- serialization ------------------------------------------------------------
    def tojson(self) -> str:
        nodes = topo_order(self._entries)
        nid = {id(n): i for i, n in enumerate(nodes)}
        out_nodes = []
        for n in nodes:
            entry = {
                "op": "null" if n.kind == "var" else n.op.name,
                "name": n.name,
                "attrs": {k: repr(v) for k, v in n.attrs.items()},
                "inputs": [[nid[id(e.node)], e.index, 0] for e in n.inputs],
            }
            ad = dict(n.attr_dict) if n.attr_dict else {}
            if n.kind == "op" and n.num_outputs() > 1:
                # foreign bindings (cpp/src/symbol.cc) need the node's
                # output count to reproduce list_outputs naming
                ad["__num_outputs__"] = str(n.num_outputs())
            if ad:
                entry["attr_dict"] = ad
            out_nodes.append(entry)
        heads = [[nid[id(e.node)], e.index, 0] for e in self._entries]
        arg_nodes = [i for i, n in enumerate(nodes) if n.kind == "var"]
        return json.dumps({"nodes": out_nodes, "arg_nodes": arg_nodes,
                           "heads": heads, "attrs": {"tpu_mx": "1"}}, indent=2)

    def save(self, fname: str):
        with open(fname, "w") as f:
            f.write(self.tojson())


# ---------------------------------------------------------------------------
# construction helpers
# ---------------------------------------------------------------------------

def Variable(name: str, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, stype=None, **kwargs) -> Symbol:
    attrs = attribute.current().get(attr)
    if shape is not None:
        attrs["__shape__"] = str(tuple(shape))
    if dtype is not None:
        attrs["__dtype__"] = str(np_dtype(dtype).name)
    if lr_mult is not None:
        attrs["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        attrs["__wd_mult__"] = str(wd_mult)
    if init is not None:
        attrs["__init__"] = init.dumps() if hasattr(init, "dumps") else str(init)
    if stype is not None:
        attrs["__storage_type__"] = str(stype)
    node = Node("var", name, attr_dict=attrs)
    return Symbol([SymbolEntry(node)])


var = Variable


def Group(symbols: Sequence[Symbol]) -> Symbol:
    entries = []
    for s in symbols:
        entries.extend(s._entries)
    return Symbol(entries)


def _parse_attr_value(v):
    """Attr values arrive as strings both from our tojson (reprs) and from
    reference-MXNet graph JSON (bare strings like "relu", "(1, 1)", "True",
    "2.0e-05").  literal_eval covers both; anything else stays a string."""
    if not isinstance(v, str):
        return v
    import ast

    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


def load_json(json_str: str) -> Symbol:
    """Parse graph JSON — ours or a reference ``prefix-symbol.json``
    (nnvm::Graph JSON: same nodes/arg_nodes/heads layout; reference writes
    op params under "attrs" (1.x) or "param" (pre-1.0), may carry
    "node_row_ptr" (ignored), and emits 2-element entries in old files)."""
    data = json.loads(json_str)
    nodes: List[Node] = []

    def entry_of(spec):
        nid, idx = spec[0], spec[1] if len(spec) > 1 else 0
        return SymbolEntry(nodes[nid], idx)

    for spec in data["nodes"]:
        attr_dict = spec.get("attr_dict", {})
        if spec["op"] == "null":
            n = Node("var", spec["name"], attr_dict=attr_dict)
        else:
            if "__control_flow__" in attr_dict:
                # per-call-site op rebuilt from its embedded subgraph json
                from . import control_flow as _cf

                op = _cf.op_from_spec(attr_dict["__control_flow__"])
            else:
                op = get_op(spec["op"])
            raw_attrs = spec.get("attrs", spec.get("param", {}))
            attrs = {k: _parse_attr_value(v) for k, v in raw_attrs.items()}
            inputs = [entry_of(e) for e in spec["inputs"]]
            n = Node("op", spec["name"], op, attrs, inputs, attr_dict)
        nodes.append(n)
    heads = [entry_of(e) for e in data["heads"]]
    return Symbol(heads)


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return load_json(f.read())


def zeros(shape, dtype="float32", **kwargs):
    import numpy as np

    sh = (shape,) if isinstance(shape, int) else tuple(shape)
    c = Variable(_name_mod.current().get(None, "zeros"), shape=sh, dtype=dtype)
    c._entries[0].node.attr_dict["__const_value__"] = "0"
    return c


def ones(shape, dtype="float32", **kwargs):
    sh = (shape,) if isinstance(shape, int) else tuple(shape)
    c = Variable(_name_mod.current().get(None, "ones"), shape=sh, dtype=dtype)
    c._entries[0].node.attr_dict["__const_value__"] = "1"
    return c


# ---------------------------------------------------------------------------
# op application — autogenerated wrappers
# ---------------------------------------------------------------------------

_DECLARED_DATA_INPUTS = {
    "FullyConnected": ["data"],
    "Convolution": ["data"],
    "Deconvolution": ["data"],
    "BatchNorm": ["data"],
    "LayerNorm": ["data"],
    "InstanceNorm": ["data"],
    "Embedding": ["data"],
    "RNN": ["data"],
    "LeakyReLU": ["data"],
    "SoftmaxOutput": ["data", "label"],
    "LinearRegressionOutput": ["data", "label"],
    "MAERegressionOutput": ["data", "label"],
    "LogisticRegressionOutput": ["data", "label"],
}


def _apply_op(op: Op, inputs: List[Symbol], attrs: dict, name: Optional[str],
              attr: Optional[dict] = None) -> Symbol:
    node_name = _name_mod.current().get(name, op.name.lstrip("_"))
    entries = []
    for s in inputs:
        if len(s._entries) != 1:
            raise MXNetError(f"{op.name}: cannot take multi-output symbol as one input")
        entries.append(s._entries[0])
    # per-call attr= overrides the ambient AttrScope (reference: every op
    # wrapper accepts attr, python/mxnet/symbol/register.py generated code)
    node = Node("op", node_name, op, attrs, entries, attribute.current().get(attr))
    n_out = op.n_outputs(attrs)
    return Symbol([SymbolEntry(node, i) for i in range(n_out)])


def _make_sym_wrapper(opname):
    op = OP_REGISTRY[opname]

    def wrapper(*args, name=None, attr=None, **kwargs):
        pos_inputs: List[Symbol] = []
        sym_kwargs: Dict[str, Symbol] = {}
        for a in args:
            if isinstance(a, Symbol):
                pos_inputs.append(a)
            elif isinstance(a, (list, tuple)) and a and isinstance(a[0], Symbol):
                pos_inputs.extend(a)
            else:
                raise TypeError(f"{opname}: positional args must be Symbols")
        for k in list(kwargs):
            if isinstance(kwargs[k], Symbol):
                sym_kwargs[k] = kwargs.pop(k)

        node_name = _name_mod.current().get(name, op.name.lstrip("_").lower())
        declared = _DECLARED_DATA_INPUTS.get(op.name)
        params, aux = _active_extra_inputs(op.name, kwargs)
        if declared is None and not params and not aux:
            # generic op: positional + any keyword symbols in given order
            inputs = pos_inputs + list(sym_kwargs.values())
            return _apply_op(op, inputs, kwargs, node_name, attr)
        # named-slot op: fill declared data slots, then params, then aux;
        # missing learnable/aux slots become auto-created variables
        # (reference: NNVM compose auto-var creation).
        order = list(declared or ["data"]) + list(params) + list(aux)
        slots: Dict[str, Symbol] = {}
        for slot, s in zip(order, pos_inputs):
            slots[slot] = s
        slots.update(sym_kwargs)
        inputs = []
        for slot in order:
            if slot in slots:
                s = slots[slot]
                if slot in aux and s._entries[0].node.kind == "var":
                    # an explicitly supplied variable feeding an aux slot IS
                    # an auxiliary state (reference: BatchNorm moving stats
                    # are aux regardless of how the var was created)
                    s._entries[0].node.attr_dict["__is_aux__"] = "1"
                inputs.append(s)
            elif slot in aux:
                v = Variable(f"{node_name}_{slot}")
                v._entries[0].node.attr_dict["__is_aux__"] = "1"
                inputs.append(v)
            else:
                # NNVM compose auto-creates variables for every missing input
                # slot — learnable params AND data slots like SoftmaxOutput's
                # label (which becomes `<name>_label`, what Module binds to)
                inputs.append(Variable(f"{node_name}_{slot}"))
        return _apply_op(op, inputs, kwargs, node_name, attr)

    wrapper.__name__ = opname
    wrapper.__doc__ = op.doc
    return wrapper
