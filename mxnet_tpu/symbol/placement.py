"""group2ctx placement: per-group segment executors + cross-device copies.

Reference: symbol-level model parallelism — ``group2ctx`` on bind
(``python/mxnet/symbol/symbol.py:1288,1434-1446``), the NNVM ``PlaceDevice``
pass + ``_CrossDeviceCopy`` insertion (``src/common/exec_utils.h:500-593``,
``src/operator/cross_device_copy.cc``), used by
``docs/faq/model_parallel_lstm.md`` / ``example/model-parallel``.

TPU-native design: one XLA program cannot mix committed single-device
placements (verified: jit raises "incompatible devices"), which is exactly
why the reference also splits the graph.  So the symbol DAG is partitioned
at bind time into contiguous same-group segments in topo order; each segment
compiles to its own jitted program whose inputs are ``device_put`` onto the
group's device (the _CrossDeviceCopy analogue — XLA's computation-follows-
data then pins the whole segment there); gradients flow backward across the
same boundaries by chaining per-segment ``jax.vjp``s with reverse copies.
Group attrs come from ``AttrScope(ctx_group=...)`` → ``__ctx_group__``,
propagated forward like PlaceDevice; an attr naming a group missing from
``group2ctx`` raises (never silently ignored).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..base import MXNetError
from .graph import Node, SymbolEntry, eval_node, input_nodes, topo_order

__all__ = ["GroupedProgram", "collect_groups"]

_DEFAULT = "__default__"


def collect_groups(entries) -> set:
    """All ctx_group names appearing in the DAG."""
    out = set()
    for n in topo_order(entries):
        g = n.attr_dict.get("__ctx_group__") or n.attr_dict.get("ctx_group")
        if g:
            out.add(g)
    return out


def _assign_groups(nodes: List[Node], valid: set) -> Dict[int, str]:
    """PlaceDevice-style forward propagation: a node keeps its own
    __ctx_group__; otherwise it inherits from its first grouped input;
    otherwise the default group."""
    gmap: Dict[int, str] = {}
    for node in nodes:
        if node.kind == "var":
            continue
        g = node.attr_dict.get("__ctx_group__") \
            or node.attr_dict.get("ctx_group")
        if g is not None and g not in valid:
            raise MXNetError(
                f"bind: node {node.name!r} has ctx_group {g!r} but "
                f"group2ctx only defines {sorted(valid)}")
        if g is None:
            for e in node.inputs:
                gi = gmap.get(id(e.node))
                if gi is not None:
                    g = gi
                    break
        gmap[id(node)] = g or _DEFAULT
    return gmap


class GroupedProgram:
    """A symbol partitioned into per-group jitted segments."""

    def __init__(self, symbol, group2ctx: Dict[str, object], default_dev,
                 grad_names: Sequence[str]):
        from ..context import Context

        def _dev(c):
            return c.jax_device if isinstance(c, Context) else c

        self._entries = symbol._entries
        self._nodes = topo_order(self._entries)
        valid = set(group2ctx)
        self._gmap = _assign_groups(self._nodes, valid)
        self._devs = {name: _dev(c) for name, c in group2ctx.items()}
        self._devs[_DEFAULT] = _dev(default_dev)
        self._grad_names = list(grad_names)

        # contiguous same-group segments over op nodes
        self._segments: List[Tuple[str, List[Node]]] = []
        for node in self._nodes:
            if node.kind == "var":
                continue
            g = self._gmap[id(node)]
            if self._segments and self._segments[-1][0] == g:
                self._segments[-1][1].append(node)
            else:
                self._segments.append((g, [node]))

        # var placement: group of the first consuming op
        self._var_group: Dict[str, str] = {}
        for node in self._nodes:
            if node.kind != "op":
                continue
            g = self._gmap[id(node)]
            for e in node.inputs:
                if e.node.kind == "var":
                    self._var_group.setdefault(e.node.name, g)
        # static per-segment external inputs (var names, cross keys)
        self._seg_in: List[Tuple[set, set]] = [
            self._seg_inputs(si) for si in range(len(self._segments))]
        self._jit_cache: Dict[tuple, object] = {}

    # -- public ---------------------------------------------------------------
    def arg_device(self, name: str):
        return self._devs[self._var_group.get(name, _DEFAULT)]

    def group_of(self, name: str) -> str:
        g = self._var_group.get(name, _DEFAULT)
        return "" if g == _DEFAULT else g

    @property
    def num_segments(self) -> int:
        return len(self._segments)

    def _seg_fn(self, si: int, is_train: bool):
        """Jitted segment body: env dict -> (produced dict, aux dict)."""
        key = (si, is_train)
        if key not in self._jit_cache:
            _, nodes = self._segments[si]

            def run(env, rng):
                values: Dict[int, tuple] = {}
                aux: Dict[str, object] = {}

                def get(e: SymbolEntry):
                    if id(e.node) in values:
                        return values[id(e.node)][e.index]
                    if e.node.kind == "var":
                        return env[e.node.name]
                    return env[f"__x_{e.node._uid}_{e.index}"]

                for node in nodes:
                    ins = [get(e) for e in node.inputs]
                    values[id(node)] = eval_node(
                        node, ins, is_train, rng,
                        aux if is_train else None)
                produced = {}
                for node in nodes:
                    for i, v in enumerate(values[id(node)]):
                        produced[f"__x_{node._uid}_{i}"] = v
                return produced, aux

            self._jit_cache[key] = jax.jit(run)
        return self._jit_cache[key]

    def _seg_inputs(self, si: int) -> Tuple[set, set]:
        """(var names, cross keys) consumed by segment si from outside it."""
        _, nodes = self._segments[si]
        node_set = {id(n) for n in nodes}
        var_names, cross = set(), set()
        for node in nodes:
            for e in node.inputs:
                if id(e.node) in node_set:
                    continue
                if e.node.kind == "var":
                    var_names.add(e.node.name)
                else:
                    cross.add(f"__x_{e.node._uid}_{e.index}")
        return var_names, cross

    def forward(self, env: Dict[str, object], rng, is_train: bool,
                with_grad: bool = False, out_cts=None):
        """Run all segments; returns (outputs, aux_updates, grads or None).

        env holds arg+aux values.  Each segment's inputs are device_put onto
        its group device (the cross-device copies); when with_grad, each
        segment records a vjp and cotangents are chained in reverse with the
        mirror copies.  out_cts (list aligned with the symbol's outputs)
        overrides the default ones-seeded head cotangents.
        """
        pool: Dict[str, object] = dict(env)
        aux_updates: Dict[str, object] = {}
        records = []  # (vjp, group, produced values, aux values)

        for si, (g, _) in enumerate(self._segments):
            dev = self._devs[g]
            var_names, cross = self._seg_in[si]
            seg_env = {k: jax.device_put(pool[k], dev)
                       for k in (var_names | cross)}
            fn = self._seg_fn(si, is_train)
            if with_grad:
                (produced, aux), vjp = jax.vjp(lambda e: fn(e, rng), seg_env)
                records.append((vjp, g, produced, aux))
            else:
                produced, aux = fn(seg_env, rng)
            pool.update(produced)
            aux_updates.update(aux)

        outs = []
        for e in self._entries:
            if e.node.kind == "var":
                outs.append(pool[e.node.name])
            else:
                outs.append(pool[f"__x_{e.node._uid}_{e.index}"])
        if not with_grad:
            return outs, aux_updates, None

        def zero_like(v):
            if jnp.issubdtype(v.dtype, jnp.inexact):
                return jnp.zeros_like(v)
            import numpy as _np
            return _np.zeros(jnp.shape(v), jax.dtypes.float0)

        def ones_like(v):
            if jnp.issubdtype(v.dtype, jnp.inexact):
                return jnp.ones_like(v)
            return zero_like(v)

        # seed head cotangents: caller-provided or ones (d of summed outputs)
        cts: Dict[str, object] = {}
        grads: Dict[str, object] = {}
        for i, (e, o) in enumerate(zip(self._entries, outs)):
            c = (jnp.asarray(out_cts[i]).astype(o.dtype)
                 if out_cts is not None else ones_like(o))
            if e.node.kind == "var":
                # a variable that IS an output contributes its head cotangent
                # directly (identity path) — dropping it loses gradient
                if (e.node.name in self._grad_names
                        and getattr(c, "dtype", None) != jax.dtypes.float0):
                    grads[e.node.name] = self._acc(grads.get(e.node.name), c)
            else:
                k = f"__x_{e.node._uid}_{e.index}"
                cts[k] = self._acc(cts.get(k), c)
        for vjp, g, produced, aux in reversed(records):
            dev = self._devs[g]
            out_ct = {k: (jax.device_put(cts.pop(k), dev)
                          if k in cts else zero_like(v))
                      for k, v in produced.items()}
            aux_ct = {k: zero_like(v) for k, v in aux.items()}
            (in_ct,) = vjp((out_ct, aux_ct))
            for k, v in in_ct.items():
                if getattr(v, "dtype", None) == jax.dtypes.float0:
                    continue
                if k.startswith("__x_"):
                    cts[k] = self._acc(cts.get(k), v)
                elif k in self._grad_names:
                    grads[k] = self._acc(grads.get(k), v)
        return outs, aux_updates, grads

    @staticmethod
    def _acc(acc, v):
        """Accumulate cotangents whose contributions may be committed to
        different group devices: copy onto the accumulator's device first
        (mixed committed devices cannot meet in one add)."""
        if acc is None:
            return v
        devs = list(acc.devices()) if hasattr(acc, "devices") else []
        if devs and hasattr(v, "devices") and list(v.devices()) != devs:
            v = jax.device_put(v, devs[0])
        return acc + v
