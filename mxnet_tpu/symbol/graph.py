"""Symbol graph core: nodes, traversal, tracing to a pure JAX function.

Reference: NNVM ``Graph/Node/Symbol`` (``src/executor/graph_executor.h:33-35``)
and the pass pipeline (Gradient / InferShape / PlanMemory — SURVEY.md §3.1).

TPU-native position: the graph here is only a *frontend* expression DAG.  All
of NNVM's passes collapse into XLA:

- InferShape/InferType  → ``jax.eval_shape`` over the traced function
- Gradient              → ``jax.grad``/``jax.vjp`` of the traced function
- PlanMemory/inplace    → XLA buffer assignment + donated arguments
- PlaceDevice/group2ctx → pjit shardings from ``__ctx_group__`` attrs
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax

from ..ops.registry import Op

# per-op parameter/aux input declarations for auto-created variables
# (reference: each op's ListArguments/ListAuxiliaryStates)
OP_EXTRA_INPUTS: Dict[str, Tuple[Tuple[str, ...], Tuple[str, ...]]] = {
    # opname: ((learnable param inputs after data...), (aux inputs))
    "FullyConnected": (("weight", "bias"), ()),
    "Convolution": (("weight", "bias"), ()),
    "Deconvolution": (("weight", "bias"), ()),
    "BatchNorm": (("gamma", "beta"), ("moving_mean", "moving_var")),
    "LayerNorm": (("gamma", "beta"), ()),
    "InstanceNorm": (("gamma", "beta"), ()),
    "Embedding": (("weight",), ()),
    "RNN": (("parameters", "state", "state_cell"), ()),
    "LeakyReLU": (("gamma",), ()),
    # int8 serving twins (docs/quantization.md): act_scale rides from the
    # quantize node; weight/wscale are the offline-quantized variables
    "_tpumx_quantized_fc_int8": (("act_scale", "weight", "wscale", "bias"),
                                 ()),
    "_tpumx_quantized_conv_int8": (("act_scale", "weight", "wscale",
                                    "bias"), ()),
}

def attr_bool(v, default=False):
    """Boolean attr that may arrive stringly-typed ("False", "0", "true" —
    the reference frontend stringifies every attr); plain truthiness would
    read "False" as True and silently change the graph structure."""
    if v is None:
        return default
    if isinstance(v, str):
        return v.strip().lower() in ("1", "true", "yes")
    return bool(v)


# ops whose extra-input list depends on attrs
def _active_extra_inputs(opname: str, attrs: dict) -> Tuple[Tuple[str, ...], Tuple[str, ...]]:
    params, aux = OP_EXTRA_INPUTS.get(opname, ((), ()))
    if opname in ("FullyConnected", "Convolution", "Deconvolution",
                  "_tpumx_quantized_fc_int8", "_tpumx_quantized_conv_int8") \
            and attr_bool(attrs.get("no_bias")):
        params = tuple(p for p in params if p != "bias")
    if opname == "LeakyReLU" and attrs.get("act_type", "leaky") != "prelu":
        params = ()
    if opname == "RNN":
        # the RNN op's own default mode is "lstm" (ops/rnn.py), so a missing
        # attr must keep the state_cell slot or the kernel runs an LSTM with
        # a silently-zero cell state
        if attrs.get("mode", "lstm") != "lstm":
            params = ("parameters", "state")
    return params, aux


class Node:
    """One graph node: a variable or an op application."""

    __slots__ = ("kind", "name", "op", "attrs", "inputs", "attr_dict", "_uid")

    _next_uid = [0]

    def __init__(self, kind: str, name: str, op: Optional[Op] = None,
                 attrs: Optional[dict] = None, inputs: Optional[List["SymbolEntry"]] = None,
                 attr_dict: Optional[dict] = None):
        self.kind = kind  # 'var' | 'op'
        self.name = name
        self.op = op
        self.attrs = attrs or {}
        self.inputs = inputs or []
        self.attr_dict = attr_dict or {}
        self._uid = Node._next_uid[0]
        Node._next_uid[0] += 1

    def num_outputs(self) -> int:
        if self.kind == "var":
            return 1
        return self.op.n_outputs(self.attrs)


class SymbolEntry:
    """(node, output_index) pair — an edge source in the DAG."""

    __slots__ = ("node", "index")

    def __init__(self, node: Node, index: int = 0):
        self.node = node
        self.index = index


def topo_order(entries: Sequence[SymbolEntry]) -> List[Node]:
    """Post-order DFS over the DAG, deduplicated (reference: nnvm DFSVisit,
    which is iterative for the same reason this is: a 1000+-op chain — a
    deeply unrolled RNN, a long residual stack — must not hit Python's
    recursion limit)."""
    seen = set()
    order: List[Node] = []
    stack: List[tuple] = []
    for e in entries:
        if id(e.node) in seen:
            continue
        seen.add(id(e.node))
        stack.append((e.node, 0))
        while stack:
            node, i = stack[-1]
            if i < len(node.inputs):
                stack[-1] = (node, i + 1)
                child = node.inputs[i].node
                if id(child) not in seen:
                    seen.add(id(child))
                    stack.append((child, 0))
            else:
                stack.pop()
                order.append(node)
    return order


def input_nodes(entries: Sequence[SymbolEntry], include_aux=True) -> List[Node]:
    """All variable nodes in traversal order."""
    out = []
    for n in topo_order(entries):
        if n.kind == "var":
            if not include_aux and n.attr_dict.get("__is_aux__"):
                continue
            out.append(n)
    return out


def eval_node(node: Node, ins: List[object], is_train: bool, rng_key=None,
              collect_aux: Optional[dict] = None) -> tuple:
    """Evaluate one op node over jax values (shared by whole-graph trace and
    the group2ctx segment executor)."""
    from ..ndarray.ndarray import _op_accepts_training

    kwargs = dict(node.attrs)
    op = node.op
    if op.rng:
        if rng_key is None:
            rng_key = jax.random.PRNGKey(0)
        kwargs["rng_key"] = jax.random.fold_in(rng_key, node._uid)
    if _op_accepts_training(op):
        kwargs["_training"] = is_train
    if op.name == "BatchNorm" and collect_aux is not None and is_train \
            and not attr_bool(kwargs.get("use_global_stats")):
        user_wants_stats = attr_bool(node.attrs.get("output_mean_var"))
        kwargs["output_mean_var"] = True
        y, mean, var = op.fn(*ins, **kwargs)
        aux_names = [e.node.name for e in node.inputs[-2:]]
        momentum = float(kwargs.get("momentum", 0.9))
        collect_aux[aux_names[0]] = momentum * ins[-2] + (1 - momentum) * mean
        collect_aux[aux_names[1]] = momentum * ins[-1] + (1 - momentum) * var
        # if the symbol itself declared output_mean_var, it has 3 outputs —
        # keep them or downstream indexing hits a 1-tuple
        return (y, mean, var) if user_wants_stats else (y,)
    out = op.fn(*ins, **kwargs)
    return tuple(out) if isinstance(out, (tuple, list)) else (out,)


def trace(entries: Sequence[SymbolEntry], env: Dict[str, object], is_train: bool,
          rng_key=None, collect_aux: Optional[dict] = None):
    """Evaluate the DAG over jax values.

    env: variable name -> jax value.  Random ops fold the node uid into
    rng_key.  When collect_aux is a dict and is_train, BatchNorm nodes place
    their (batch_mean, batch_var) under their aux variable names so the
    executor can update running stats functionally.
    """
    values: Dict[int, tuple] = {}

    for node in topo_order(entries):
        if node.kind == "var":
            if node.name not in env:
                raise ValueError(f"unbound variable {node.name!r}")
            values[id(node)] = (env[node.name],)
            continue
        ins = [values[id(e.node)][e.index] for e in node.inputs]
        values[id(node)] = eval_node(node, ins, is_train, rng_key, collect_aux)

    return [values[id(e.node)][e.index] for e in entries]
