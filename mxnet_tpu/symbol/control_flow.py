"""Traceable control-flow ops: foreach / while_loop / cond.

Reference: ``src/operator/control_flow.cc:1256`` (``_foreach``), ``:1317``
(``_while_loop``), ``:1379`` (``_cond``) — subgraph ops with full backward,
plus the Python subgraph-cutting frontend
(``python/mxnet/symbol/contrib.py`` _cut_subgraph / AttrScope marking).

TPU-native design: the body is built as a normal Symbol sub-DAG (marked with
an ``__subgraph_name__`` attribute scope, exactly the reference's cutting
trick), then packaged into a per-call-site Op whose ``fn`` lowers the loop to
``lax.scan`` / masked scan / ``lax.cond``.  Because the subgraph traces to
pure JAX, gradients come from the same ``jax.vjp`` path as every other op —
no bespoke backward pass (the reference needs ~2k LoC of subgraph gradient
plumbing).  The resulting Symbol binds/hybridizes like any other; the whole
loop compiles into the enclosing XLA program with static shapes.

Serialization: each control-flow node carries a ``__control_flow__`` attr
holding its subgraph(s) as nested symbol JSON plus the boundary-name lists
(the analogue of the reference embedding subgraphs in symbol JSON,
control_flow.cc:1256-1310); ``load_json`` hands that spec back to
:func:`op_from_spec`, which rebuilds the per-call-site Op — so
foreach/while/cond symbols round-trip through ``tojson``/``load``.
"""
from __future__ import annotations

import itertools
import json as _json
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ops.registry import Op
from .. import attribute
from .graph import Node, SymbolEntry, topo_order, trace
from .symbol import Symbol, Variable, _apply_op

__all__ = ["foreach", "while_loop", "cond", "op_from_spec"]

_uid = itertools.count()


def _as_sym_list(x) -> List[Symbol]:
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _pack_like(values, template):
    """Return values as a list iff the user passed a list."""
    if isinstance(template, (list, tuple)):
        return list(values)
    return values[0]


def _cut_subgraph(entries: List[SymbolEntry], scope: str,
                  bound_names: set) -> Tuple[List[SymbolEntry], List[str], List[Symbol]]:
    """Split the DAG reachable from `entries` at the subgraph boundary.

    Nodes carrying ``__subgraph_name__ == scope`` are inner; anything else is
    outer and becomes a closure input: the edge is replaced by a fresh inner
    variable, and the outer entry is returned as a Symbol to be wired as an
    input of the control-flow node.  Free inner variables that are not bound
    loop variables (e.g. auto-created layer params) are closures too, passed
    through by identity (reference: contrib.py subgraph input collection).
    """
    memo: Dict[int, Node] = {}
    cut: Dict[Tuple[int, int], SymbolEntry] = {}
    closure_names: List[str] = []
    closure_syms: List[Symbol] = []

    def rewrite(entry: SymbolEntry) -> SymbolEntry:
        n = entry.node
        if n.kind == "var":
            if n.name in bound_names:
                return entry
            if n.name not in closure_names:
                closure_names.append(n.name)
                closure_syms.append(Symbol([SymbolEntry(n)]))
            return entry
        if n.attr_dict.get("__subgraph_name__") != scope:
            # outer op output crossing into the subgraph
            key = (id(n), entry.index)
            if key not in cut:
                cname = f"{scope}_closure{len(closure_names)}"
                var_node = Node("var", cname,
                                attr_dict={"__subgraph_name__": scope})
                cut[key] = SymbolEntry(var_node)
                closure_names.append(cname)
                closure_syms.append(Symbol([entry]))
            return cut[key]
        if id(n) not in memo:
            nn = Node(n.kind, n.name, n.op, dict(n.attrs), [],
                      dict(n.attr_dict))
            memo[id(n)] = nn        # placed before recursion: DAGs only
            nn.inputs = [rewrite(e) for e in n.inputs]
        return SymbolEntry(memo[id(n)], entry.index)

    new_entries = [rewrite(e) for e in entries]
    return new_entries, closure_names, closure_syms


def foreach(body, data, init_states, name: str = None):
    """Scan `body` over axis 0 of `data`, threading `states`.

    body(data_t, states) -> (outputs, new_states); returns (stacked outputs,
    final states).  Lowers to ``lax.scan`` — gradients, jit and hybridize all
    work.  Reference: control_flow.cc:1256 `_foreach`.
    """
    scope = name or f"_foreach{next(_uid)}"
    data_list = _as_sym_list(data)
    state_list = _as_sym_list(init_states)

    item_names = [f"{scope}_item{i}" for i in range(len(data_list))]
    state_names = [f"{scope}_state{i}" for i in range(len(state_list))]
    with attribute.AttrScope(__subgraph_name__=scope):
        item_vars = [Variable(n) for n in item_names]
        state_vars = [Variable(n) for n in state_names]
        out, new_states = body(_pack_like(item_vars, data),
                               _pack_like(state_vars, init_states))
    out_list = _as_sym_list(out)
    new_state_list = _as_sym_list(new_states)
    if len(new_state_list) != len(state_list):
        raise MXNetError(
            f"foreach: body returned {len(new_state_list)} states, "
            f"expected {len(state_list)}")

    head_entries = [s._entries[0] for s in out_list + new_state_list]
    sub_entries, closure_names, closure_syms = _cut_subgraph(
        head_entries, scope, set(item_names + state_names))

    n_state, n_out = len(state_list), len(out_list)
    op = _make_foreach_op(sub_entries, item_names, state_names,
                          closure_names, n_out)
    res = _apply_op(op, data_list + state_list + closure_syms, {}, scope)
    _stamp_spec(res, {"kind": "foreach",
                      "subgraph": Symbol(sub_entries).tojson(),
                      "item_names": item_names, "state_names": state_names,
                      "closure_names": closure_names, "n_out": n_out})
    outputs = [res[i] for i in range(n_out)]
    states = [res[n_out + i] for i in range(n_state)]
    return _pack_like(outputs, out), _pack_like(states, init_states)


def _stamp_spec(res: Symbol, spec: dict):
    res._entries[0].node.attr_dict["__control_flow__"] = _json.dumps(spec)


def _make_foreach_op(sub_entries, item_names, state_names, closure_names,
                     n_out):
    n_data, n_state = len(item_names), len(state_names)

    def _foreach_fn(*arrays, _training=True, rng_key=None):
        datas = arrays[:n_data]
        init = arrays[n_data:n_data + n_state]
        closures = arrays[n_data + n_state:]
        cenv = dict(zip(closure_names, closures))

        def step(carry, xs):
            t, state = carry
            env = dict(cenv)
            env.update(zip(state_names, state))
            env.update(zip(item_names, xs))
            # fresh randomness per timestep (dropout masks must differ)
            key = None if rng_key is None else jax.random.fold_in(rng_key, t)
            outs = trace(sub_entries, env, _training, key)
            return (t + 1, tuple(outs[n_out:])), tuple(outs[:n_out])

        (_, carry), ys = jax.lax.scan(
            step, (jnp.int32(0), tuple(init)), tuple(datas))
        return tuple(ys) + tuple(carry)

    return Op("_foreach", _foreach_fn, num_outputs=n_out + n_state, rng=True)


def while_loop(cond_fn, func, loop_vars, max_iterations, name: str = None):
    """Run `func` while `cond_fn(*loop_vars)` is true, up to max_iterations.

    func(*loop_vars) -> (outputs, new_loop_vars); returns (stacked outputs
    padded with zeros to max_iterations, final loop_vars).  Lowers to a
    masked ``lax.scan`` (fixed trip count keeps shapes static for XLA; the
    mask freezes state and zeroes outputs once the condition fails), which
    keeps the whole loop differentiable.  Reference: control_flow.cc:1317.
    """
    if max_iterations is None:
        raise MXNetError("while_loop: max_iterations is required for the "
                         "traceable path (static shapes)")
    scope = name or f"_while{next(_uid)}"
    lv_list = _as_sym_list(loop_vars)
    lv_names = [f"{scope}_lv{i}" for i in range(len(lv_list))]

    with attribute.AttrScope(__subgraph_name__=scope):
        lv_vars = [Variable(n) for n in lv_names]
        cond_out = cond_fn(*lv_vars)
        out, new_lv = func(*lv_vars)
    out_list = _as_sym_list(out)
    new_lv_list = _as_sym_list(new_lv)
    if len(new_lv_list) != len(lv_list):
        raise MXNetError(
            f"while_loop: func returned {len(new_lv_list)} loop_vars, "
            f"expected {len(lv_list)}")

    heads = [cond_out._entries[0]] + \
        [s._entries[0] for s in out_list + new_lv_list]
    sub_entries, closure_names, closure_syms = _cut_subgraph(
        heads, scope, set(lv_names))

    n_lv, n_out, T = len(lv_list), len(out_list), int(max_iterations)
    op = _make_while_op(sub_entries, lv_names, closure_names, n_out, T)
    res = _apply_op(op, lv_list + closure_syms, {}, scope)
    _stamp_spec(res, {"kind": "while_loop",
                      "subgraph": Symbol(sub_entries).tojson(),
                      "lv_names": lv_names, "closure_names": closure_names,
                      "n_out": n_out, "max_iterations": T})
    outputs = [res[i] for i in range(n_out)]
    states = [res[n_out + i] for i in range(n_lv)]
    return outputs, _pack_like(states, loop_vars)


def _make_while_op(sub_entries, lv_names, closure_names, n_out, T):
    n_lv = len(lv_names)

    def _while_fn(*arrays, _training=True, rng_key=None):
        lv0 = arrays[:n_lv]
        closures = arrays[n_lv:]
        cenv = dict(zip(closure_names, closures))

        def step(carry, _):
            t, lv, active = carry
            env = dict(cenv)
            env.update(zip(lv_names, lv))
            key = None if rng_key is None else jax.random.fold_in(rng_key, t)
            outs = trace(sub_entries, env, _training, key)
            c = outs[0]
            run = jnp.logical_and(active,
                                  jnp.squeeze(c).astype(jnp.bool_))
            body_out = outs[1:1 + n_out]
            body_lv = outs[1 + n_out:]
            new_lv = tuple(jnp.where(run, b, a) for a, b in zip(lv, body_lv))
            ys = tuple(jnp.where(run, o, jnp.zeros_like(o)) for o in body_out)
            return (t + 1, new_lv, run), ys

        (_, final_lv, _), ys = jax.lax.scan(
            step, (jnp.int32(0), tuple(lv0), jnp.bool_(True)), None, length=T)
        return tuple(ys) + tuple(final_lv)

    return Op("_while_loop", _while_fn, num_outputs=n_out + n_lv, rng=True)


def cond(pred, then_func, else_func, name: str = None):
    """Branch on a scalar predicate symbol; lowers to ``lax.cond``.

    Both branches must produce matching shapes/dtypes (XLA requirement, same
    as the reference's shape inference on _cond).  Reference:
    control_flow.cc:1379.
    """
    scope = name or f"_cond{next(_uid)}"
    with attribute.AttrScope(__subgraph_name__=scope):
        then_out = then_func()
        else_out = else_func()
    then_list = _as_sym_list(then_out)
    else_list = _as_sym_list(else_out)
    if len(then_list) != len(else_list):
        raise MXNetError("cond: branches must return the same number of "
                         f"outputs ({len(then_list)} vs {len(else_list)})")

    n_out = len(then_list)
    then_entries, then_cnames, then_csyms = _cut_subgraph(
        [s._entries[0] for s in then_list], scope, set())
    else_entries, else_cnames, else_csyms = _cut_subgraph(
        [s._entries[0] for s in else_list], scope, set())

    op = _make_cond_op(then_entries, else_entries, then_cnames, else_cnames,
                       n_out)
    res = _apply_op(op, [pred] + then_csyms + else_csyms, {}, scope)
    _stamp_spec(res, {"kind": "cond",
                      "then_subgraph": Symbol(then_entries).tojson(),
                      "else_subgraph": Symbol(else_entries).tojson(),
                      "then_cnames": then_cnames, "else_cnames": else_cnames,
                      "n_out": n_out})
    outputs = [res[i] for i in range(n_out)] if n_out > 1 else res
    return _pack_like(_as_sym_list(outputs), then_out)


def _make_cond_op(then_entries, else_entries, then_cnames, else_cnames,
                  n_out):
    n_then = len(then_cnames)

    def _cond_fn(pred_v, *closures, _training=True, rng_key=None):
        tc = closures[:n_then]
        ec = closures[n_then:]

        def then_branch(_):
            outs = trace(then_entries, dict(zip(then_cnames, tc)),
                         _training, rng_key)
            return tuple(outs)

        def else_branch(_):
            outs = trace(else_entries, dict(zip(else_cnames, ec)),
                         _training, rng_key)
            return tuple(outs)

        picked = jax.lax.cond(jnp.squeeze(pred_v).astype(jnp.bool_),
                              then_branch, else_branch, None)
        return picked if n_out > 1 else picked[0]

    return Op("_cond", _cond_fn, num_outputs=n_out, rng=True)


def op_from_spec(spec_json: str) -> Op:
    """Rebuild a control-flow node's per-call-site Op from its serialized
    ``__control_flow__`` spec (used by ``load_json``; nested control flow
    recurses through the same path)."""
    from .symbol import load_json

    spec = _json.loads(spec_json)
    kind = spec["kind"]
    if kind == "foreach":
        return _make_foreach_op(load_json(spec["subgraph"])._entries,
                                spec["item_names"], spec["state_names"],
                                spec["closure_names"], int(spec["n_out"]))
    if kind == "while_loop":
        return _make_while_op(load_json(spec["subgraph"])._entries,
                              spec["lv_names"], spec["closure_names"],
                              int(spec["n_out"]),
                              int(spec["max_iterations"]))
    if kind == "cond":
        return _make_cond_op(load_json(spec["then_subgraph"])._entries,
                             load_json(spec["else_subgraph"])._entries,
                             spec["then_cnames"], spec["else_cnames"],
                             int(spec["n_out"]))
    raise MXNetError(f"unknown control-flow kind {kind!r}")
