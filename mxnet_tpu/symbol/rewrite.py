"""Shared symbolic DAG-rewrite engine (docs/amp.md, docs/quantization.md).

Both graph-rewriting passes in the stack — AMP's casting policy
(:func:`mxnet_tpu.amp.convert_symbol`) and int8 quantization
(:func:`mxnet_tpu.quantization.convert_symbol`) — are the same walk: visit
the DAG in topo order, keep a static *tag* per producing node (a dtype
state like ``"f32"``/``"bfloat16"``/``"int8"``), insert the MINIMAL set of
boundary-conversion nodes (``amp_cast`` for AMP, quantize/dequantize for
int8) with a conversion cache so a value consumed twice at the same tag
pays one node, and rebuild the symbol with variables shared (names and
bindings stay stable).  This module is that walk, extracted from
``amp/convert.py`` — the AMP goldens in tests/test_amp_golden.py pin the
extraction byte-identical — so each pass only supplies its policy:

- :func:`rewrite_graph` — the tagged topo walk.  The ``visit`` callback
  sees each op node with its inputs already remapped into the new graph
  and decides what happens: return ``None`` for a verbatim clone with tag
  propagation, a ``(inputs, attrs, tag)`` triple for a clone with
  converted inputs / amended attrs, or a :class:`Replaced` for a full
  node-replacement (the quantize → quantized-op → dequantize sandwich).
- :func:`strip_ops` — the inverse pass: drop single-input passthrough
  nodes by op name (``remove_amp_cast``'s engine), rebuilding only the
  nodes whose inputs actually changed.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence, Tuple

__all__ = ["PROPAGATE", "Replaced", "RewriteContext", "rewrite_graph",
           "strip_ops"]

# sentinel out-tag: derive the node's tag from its (new) input tags —
# one distinct input tag propagates, mixed tags become unknown (None)
PROPAGATE = object()


class Replaced:
    """A ``visit`` result that substitutes a whole subgraph for the node:
    ``entries[i]`` stands in for the original node's output ``i``."""

    __slots__ = ("entries", "tag")

    def __init__(self, entries, tag=None):
        self.entries = list(entries)
        self.tag = tag


class RewriteContext:
    """Walk state handed to the ``visit`` policy: per-node tags, the
    entry remap, and the cached boundary-conversion inserter."""

    def __init__(self, make_conversion: Optional[Callable], default_tag):
        self._make = make_conversion
        self.default_tag = default_tag
        self.entry_map: Dict[tuple, object] = {}
        self._tag: Dict[int, Optional[str]] = {}
        self._cache: Dict[tuple, object] = {}
        self.counter = 0

    def tag_of(self, entry) -> Optional[str]:
        """The producing node's tag (None = unknown)."""
        return self._tag.get(id(entry.node))

    def set_tag(self, node, tag) -> None:
        self._tag[id(node)] = tag

    def convert(self, entry, tag):
        """Insert (or reuse) a boundary conversion of ``entry`` to ``tag``.

        Cached per ``(producer, output index, tag)`` — a chain of
        same-policy consumers pays ONE conversion node, the minimal-cast
        property the AMP tests assert.  The policy's ``make_conversion``
        builds the node and names it from the running ordinal (the
        ordinal only advances on cache misses, keeping generated names
        dense and deterministic)."""
        from .graph import SymbolEntry

        key = (id(entry.node), entry.index, tag)
        ent = self._cache.get(key)
        if ent is None:
            self.counter += 1
            node, node_tag = self._make(entry, tag, self.counter)
            self.set_tag(node, node_tag)
            ent = SymbolEntry(node, 0)
            self._cache[key] = ent
        return ent


def rewrite_graph(symbol, visit: Callable, *,
                  make_conversion: Optional[Callable] = None,
                  var_tag: Optional[Callable] = None,
                  default_tag: str = "f32"):
    """Rebuild ``symbol`` under a tagged-walk rewrite policy.

    Parameters
    ----------
    visit : callable(node, inputs, ctx)
        Called for every op node with ``inputs`` already remapped into
        the new graph.  Returns ``None`` (verbatim clone, tag
        propagation), ``(inputs, attrs, tag)`` (clone with those inputs
        and attrs; ``tag`` may be :data:`PROPAGATE`), or a
        :class:`Replaced`.
    make_conversion : callable(entry, tag, ordinal) -> (Node, node_tag)
        Builds one boundary-conversion node (see
        :meth:`RewriteContext.convert`).
    var_tag : callable(node) -> tag
        Tag for variable nodes (default: ``default_tag`` — simple_bind
        creates f32 variables unless overridden, and a mis-tagged
        variable costs at worst a redundant conversion, never a wrong
        result).
    default_tag : str
        The tag assumed for nodes with no inputs.

    Variables are SHARED with the input symbol (names/bindings stay
    stable); every op node is cloned.  The input symbol is left
    untouched.
    """
    from .graph import Node, SymbolEntry, topo_order
    from .symbol import Symbol

    ctx = RewriteContext(make_conversion, default_tag)

    def mapped(e: "SymbolEntry") -> "SymbolEntry":
        return ctx.entry_map[(id(e.node), e.index)]

    for node in topo_order(symbol._entries):
        if node.kind == "var":
            ctx.entry_map[(id(node), 0)] = SymbolEntry(node, 0)
            ctx.set_tag(node, var_tag(node) if var_tag is not None
                        else default_tag)
            continue
        new_inputs = [mapped(e) for e in node.inputs]
        result = visit(node, new_inputs, ctx)
        if isinstance(result, Replaced):
            for i, ent in enumerate(result.entries):
                ctx.entry_map[(id(node), i)] = ent
                ctx.set_tag(ent.node, result.tag)
            continue
        if result is None:
            attrs, out_tag = dict(node.attrs), PROPAGATE
        else:
            new_inputs, attrs, out_tag = result
        if out_tag is PROPAGATE:
            in_tags = {ctx.tag_of(e) for e in new_inputs} or {default_tag}
            out_tag = in_tags.pop() if len(in_tags) == 1 else None
        new_node = Node("op", node.name, op=node.op, attrs=attrs,
                        inputs=new_inputs, attr_dict=dict(node.attr_dict))
        for i in range(new_node.num_outputs()):
            ctx.entry_map[(id(node), i)] = SymbolEntry(new_node, i)
        ctx.set_tag(new_node, out_tag)
    return Symbol([mapped(e) for e in symbol._entries])


def strip_ops(symbol, op_names: Sequence[str]):
    """Drop every single-input passthrough node whose op name is in
    ``op_names``, wiring consumers to the stripped node's input —
    ``remove_amp_cast``'s engine, reusable for any inserted-boundary op
    family.  Returns the input symbol unchanged when nothing matched."""
    from .graph import Node, SymbolEntry, topo_order
    from .symbol import Symbol

    names = frozenset(op_names)
    entry_map: Dict[tuple, SymbolEntry] = {}

    def mapped(e: SymbolEntry) -> SymbolEntry:
        return entry_map.get((id(e.node), e.index), e)

    changed = False
    for node in topo_order(symbol._entries):
        if node.kind == "var":
            continue
        if node.op.name in names:
            entry_map[(id(node), 0)] = mapped(node.inputs[0])
            changed = True
            continue
        new_inputs = [mapped(e) for e in node.inputs]
        if any(a.node is not b.node or a.index != b.index
               for a, b in zip(new_inputs, node.inputs)):
            new_node = Node("op", node.name, op=node.op,
                            attrs=dict(node.attrs), inputs=new_inputs,
                            attr_dict=dict(node.attr_dict))
            for i in range(new_node.num_outputs()):
                entry_map[(id(node), i)] = SymbolEntry(new_node, i)
    if not changed:
        return symbol
    return Symbol([mapped(e) for e in symbol._entries])
