"""Pipeline-stage discovery over the symbol DAG (docs/sharding.md §pp).

``Module.fit`` gains a ``pp`` mesh axis (``TPUMX_PP_DEVICES``) the same way
it gained ``mp``: the executor keeps ONE donated fused program, and this
module supplies the graph analysis that makes a generic symbol pipelinable —
the reference's ``group2ctx`` inter-layer model parallelism
(src/executor/graph_executor.cc AssignContext) recast as true GPipe
round-robin scheduling instead of cross-device copies.

A symbol is *stage-stackable* when its op DAG contains a chain of ``S × k``
isomorphic units — same op sequence, same attrs, same parameter shapes, same
boundary activation shape/dtype (a deep MLP trunk, an unrolled residual
tower, a transformer block stack lowered to symbols).  The plan splits the
graph into:

- **prologue**: everything the pipeline input depends on (embedding/input
  projection) — computed replicated on every pp rank; its parameter
  cotangents are nonzero only on stage 0 (the microbatch injection is gated
  by ``rank == 0``), so they combine with a pp-psum;
- **body**: the repeated units, ``k`` per stage.  Stage ``s`` executes the
  TEMPLATE segment (stage 0's ops) with stage ``s``'s parameters — the
  in-program equivalent of stacking the per-stage param trees and slicing by
  ``lax.axis_index("pp")``.  Grad combination: pp-psum (disjoint per rank);
- **epilogue**: everything downstream of the body (head + loss), computed
  replicated on the ``psum_bcast``-replicated pipeline outputs; its
  parameter gradients are already exact and replica-invariant (identity
  combination).

Restrictions enforced at plan time (violations fall back to the dp×mp mesh
with a logged reason, never an error mid-fit): the body carries no RNG ops
(stage uids would collide) and no aux states (BatchNorm running stats can't
commit from inside the scanned tick loop); no parameter is shared between
regions; prologue activations never skip past the body.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError
from .graph import (Node, SymbolEntry, _active_extra_inputs, eval_node,
                    topo_order)

__all__ = ["PipelinePlan", "PlanError", "plan_pipeline", "node_output_structs"]


class PlanError(MXNetError):
    """The symbol cannot be split into the requested pipeline stages; the
    message names the failed condition so the fallback log line is
    actionable."""


def node_output_structs(entries: Sequence[SymbolEntry],
                        env_structs: Dict[str, object]) -> Dict[int, tuple]:
    """Abstractly evaluate the DAG: ``{id(node): (ShapeDtypeStruct, ...)}``
    for every node, via ``jax.eval_shape`` (no FLOPs, no device memory)."""
    import jax

    order = topo_order(entries)

    def probe(env):
        values: Dict[int, tuple] = {}
        outs = []
        for node in order:
            if node.kind == "var":
                values[id(node)] = (env[node.name],)
            else:
                ins = [values[id(e.node)][e.index] for e in node.inputs]
                values[id(node)] = eval_node(node, ins, True,
                                             jax.random.PRNGKey(0),
                                             collect_aux={})
            outs.append(values[id(node)])
        return outs

    shaped = jax.eval_shape(probe, dict(env_structs))
    return {id(node): tuple(shaped[i]) for i, node in enumerate(order)}


def _sig_of(struct) -> tuple:
    return (tuple(struct.shape), str(struct.dtype))


@dataclass
class PipelinePlan:
    """The result of :func:`plan_pipeline`: enough structure for the
    executor to trace the pipelined forward inside its fused program."""

    entries: Sequence[SymbolEntry]
    n_stages: int
    prologue_nodes: List[Node]
    body_nodes: List[Node]                 # all stages, execution order
    template_nodes: List[Node]             # stage 0's segment
    template_param_names: List[str]        # ordered var inputs of template
    stage_param_names: List[List[str]]     # per stage, aligned with template
    boundary: SymbolEntry                  # the body's input edge
    epilogue_nodes: List[Node]
    param_group: Dict[str, str] = field(default_factory=dict)
    units_per_stage: int = 1

    def pp_combine(self, name: str) -> str:
        """Gradient combination over the pp axis for parameter ``name``:
        ``"psum"`` (prologue + stage params — rank-gated contributions) or
        ``"none"`` (epilogue params — already exact and replicated)."""
        return "psum" if self.param_group.get(name) in ("prologue",
                                                        "stage") else "none"

    def describe(self) -> str:
        return (f"pp plan: {len(self.prologue_nodes)} prologue ops | "
                f"{self.n_stages} stages × {self.units_per_stage} units "
                f"({len(self.template_nodes)} ops/stage) | "
                f"{len(self.epilogue_nodes)} epilogue ops")

    # -- the traced pipelined forward (runs INSIDE shard_map) -------------------
    def apply(self, env: Dict[str, object], is_train: bool, rng_key,
              collect_aux: Optional[dict], n_micro: int,
              axis_name: str = "pp"):
        """Drop-in for ``symbol.graph.trace`` over the full entry list, with
        the body executed as a :func:`~mxnet_tpu.parallel.pipeline
        .pipeline_apply` round-robin over ``n_micro`` microbatches."""
        import jax.numpy as jnp
        from jax import lax

        from ..parallel.pipeline import pipeline_apply, psum_bcast

        values: Dict[int, tuple] = {}
        for node in topo_order(self.entries):
            if node.kind == "var":
                if node.name not in env:
                    raise ValueError(f"unbound variable {node.name!r}")
                values[id(node)] = (env[node.name],)

        def run(nodes, aux):
            for node in nodes:
                ins = [values[id(e.node)][e.index] for e in node.inputs]
                values[id(node)] = eval_node(node, ins, is_train, rng_key,
                                             aux)

        run(self.prologue_nodes, collect_aux)
        x = values[id(self.boundary.node)][self.boundary.index]
        B = x.shape[0]
        if B % n_micro:
            raise MXNetError(
                f"pipeline: local batch {B} not divisible by "
                f"{n_micro} microbatches")
        xmb = x.reshape((n_micro, B // n_micro) + x.shape[1:])
        # stage-stacked params: one (S, ...) stack per template slot, this
        # rank's stage sliced out by its pp coordinate
        ridx = lax.axis_index(axis_name)
        my_params = {}
        for ti, tname in enumerate(self.template_param_names):
            stacked = jnp.stack([env[self.stage_param_names[s][ti]]
                                 for s in range(self.n_stages)])
            my_params[tname] = lax.dynamic_index_in_dim(stacked, ridx,
                                                        keepdims=False)

        template = self.template_nodes
        last = template[-1]

        def stage_fn(params, xin):
            vals: Dict[int, tuple] = {}
            for node in template:
                ins = []
                for e in node.inputs:
                    if e.node.kind == "var":
                        ins.append(params[e.node.name])
                    elif id(e.node) in vals:
                        ins.append(vals[id(e.node)][e.index])
                    else:
                        ins.append(xin)  # the stage's boundary input
                # body carries no aux states by construction (plan_pipeline)
                vals[id(node)] = eval_node(node, ins, is_train, rng_key,
                                           None)
            return vals[id(last)][0]

        out = pipeline_apply(stage_fn, my_params, xmb, axis_name)
        out = psum_bcast(out, axis_name)
        y = out.reshape((B,) + out.shape[2:])
        values[id(self.body_nodes[-1])] = (y,)
        run(self.epilogue_nodes, collect_aux)
        return [values[id(e.node)][e.index] for e in self.entries]


def _consumers(entries) -> Dict[int, List[Tuple[Node, int]]]:
    out: Dict[int, List[Tuple[Node, int]]] = {}
    for node in topo_order(entries):
        for e in node.inputs:
            out.setdefault(id(e.node), []).append((node, e.index))
    return out


def _node_token(node: Node, structs, env_structs) -> tuple:
    attrs = tuple(sorted((k, str(v)) for k, v in node.attrs.items()))
    param_sig = tuple(_sig_of(env_structs[e.node.name])
                      for e in node.inputs if e.node.kind == "var")
    out_sig = tuple(_sig_of(s) for s in structs[id(node)])
    return (node.op.name, attrs, param_sig, out_sig)


def plan_pipeline(entries: Sequence[SymbolEntry], n_stages: int,
                  env_structs: Dict[str, object],
                  input_names: Sequence[str] = ()) -> PipelinePlan:
    """Split the symbol into ``n_stages`` isomorphic pipeline stages.

    ``env_structs`` maps every variable name to a ``ShapeDtypeStruct`` (or
    any shape/dtype carrier) at the BOUND shapes; ``input_names`` are the
    data/label/state variables (exempt from the parameter-exclusivity
    checks — their values are environment-available on every rank).
    Raises :class:`PlanError` naming the failed condition when the graph is
    not stage-stackable.
    """
    import jax

    n_stages = int(n_stages)
    if n_stages < 2:
        raise PlanError("pipeline needs n_stages >= 2")
    env_structs = {
        k: jax.ShapeDtypeStruct(tuple(v.shape), v.dtype)
        for k, v in env_structs.items()}
    order = topo_order(entries)
    op_nodes = [n for n in order if n.kind == "op"]
    if not op_nodes:
        raise PlanError("empty graph")
    consumers = _consumers(entries)
    inputs = set(input_names)
    out_node_ids = {id(e.node) for e in entries}
    structs = node_output_structs(entries, env_structs)

    def stageable(node: Node) -> bool:
        if node.op is None or getattr(node.op, "rng", False):
            return False  # stage uids collide across ranks
        _, aux = _active_extra_inputs(node.op.name, node.attrs)
        if aux:
            return False  # running stats can't commit from the tick loop
        op_ins = [e for e in node.inputs if e.node.kind == "op"]
        if len(op_ins) != 1 or node.num_outputs() != 1:
            return False
        for e in node.inputs:
            if e.node.kind == "var":
                if e.node.name in inputs:
                    return False  # body must not read data/labels directly
                if len(consumers.get(id(e.node), [])) != 1:
                    return False  # shared (tied) param spans stages
        return True

    # maximal single-consumer chains of stageable nodes
    def links_to(prev: Node, node: Node) -> bool:
        if id(prev) in out_node_ids:
            return False  # an exported activation pins the cut here
        cons = consumers.get(id(prev), [])
        return len(cons) == 1 and cons[0][0] is node

    runs: List[List[Node]] = []
    in_run: Dict[int, bool] = {}
    for node in op_nodes:
        if not stageable(node) or in_run.get(id(node)):
            continue
        run = [node]
        in_run[id(node)] = True
        while True:
            nxt = consumers.get(id(run[-1]), [])
            if len(nxt) != 1:
                break
            cand = nxt[0][0]
            if cand.kind != "op" or not stageable(cand) \
                    or not links_to(run[-1], cand) or in_run.get(id(cand)):
                break
            run.append(cand)
            in_run[id(cand)] = True
        runs.append(run)

    # best repeated unit across all runs: maximize covered ops S*k*u
    best = None  # (coverage, run, start, u, r_use)
    for run in runs:
        L = len(run)
        tokens = [_node_token(n, structs, env_structs) for n in run]

        def in_sig(idx: int) -> tuple:
            for e in run[idx].inputs:
                if e.node.kind == "op":
                    return _sig_of(structs[id(e.node)][e.index])
            e = run[idx].inputs[0]  # data slot by op convention
            return _sig_of(env_structs[e.node.name])

        for u in range(1, L // n_stages + 1):
            for start in range(L - n_stages * u + 1):
                unit = tokens[start:start + u]
                r = 1
                while start + (r + 1) * u <= L and \
                        tokens[start + r * u:start + (r + 1) * u] == unit:
                    r += 1
                r_use = r - (r % n_stages)
                if r_use < n_stages:
                    continue
                # ring requirement: unit output == unit input shape/dtype
                out_sig = tokens[start][3]
                if len(out_sig) != 1 or out_sig[0] != in_sig(start) \
                        or tokens[start + u - 1][3][0] != in_sig(start):
                    continue
                coverage = r_use * u
                if best is None or coverage > best[0]:
                    # leading extras (r - r_use units) stay in the prologue
                    best = (coverage, run, start + (r - r_use) * u, u, r_use)

    if best is None:
        raise PlanError(
            f"no chain of >= {n_stages} isomorphic units (same ops, attrs, "
            f"param shapes, and boundary activation) found")
    _, run, start, u, r_use = best
    k = r_use // n_stages
    body = run[start:start + r_use * u]
    body_ids = {id(n) for n in body}
    template = body[:k * u]
    boundary = next((e for e in body[0].inputs if e.node.kind == "op"),
                    body[0].inputs[0])

    # ancestors of the boundary (the prologue side of the cut)
    anc_ids = set()
    stack = [boundary.node]
    while stack:
        n = stack.pop()
        if id(n) in anc_ids:
            continue
        anc_ids.add(id(n))
        stack.extend(e.node for e in n.inputs)
    # the cut: prologue OP activations may only feed the prologue (and the
    # boundary may feed the body head) — a skip edge past the body would
    # need a second crossing the ring cannot carry
    for n in order:
        if n.kind != "op" or id(n) not in anc_ids:
            continue
        for c, _ in consumers.get(id(n), []):
            if id(c) in anc_ids:
                continue
            if n is boundary.node and c is body[0]:
                continue
            raise PlanError(
                f"prologue op {n.name!r} feeds past the pipeline boundary "
                f"into {c.name!r}")

    prologue = [n for n in order if n.kind == "op" and id(n) in anc_ids]
    epilogue = [n for n in order if n.kind == "op" and id(n) not in anc_ids
                and id(n) not in body_ids]

    # parameter grouping (gradient combination over pp)
    epi_ids = {id(n) for n in epilogue}
    param_group: Dict[str, str] = {}
    for n in order:
        if n.kind != "var" or n.name in inputs:
            continue
        where = set()
        for c, _ in consumers.get(id(n), []):
            if id(c) in body_ids:
                where.add("stage")
            elif id(c) in anc_ids:
                where.add("prologue")
            elif id(c) in epi_ids:
                where.add("epilogue")
        if len(where) > 1:
            raise PlanError(
                f"parameter {n.name!r} is shared across pipeline regions "
                f"({sorted(where)})")
        if where:
            param_group[n.name] = where.pop()

    template_param_names = [e.node.name for node in template
                            for e in node.inputs if e.node.kind == "var"]
    stage_param_names = []
    for s in range(n_stages):
        seg = body[s * k * u:(s + 1) * k * u]
        stage_param_names.append(
            [e.node.name for node in seg
             for e in node.inputs if e.node.kind == "var"])

    return PipelinePlan(
        entries=entries, n_stages=n_stages, prologue_nodes=prologue,
        body_nodes=body, template_nodes=template,
        template_param_names=template_param_names,
        stage_param_names=stage_param_names, boundary=boundary,
        epilogue_nodes=epilogue, param_group=param_group,
        units_per_stage=k)
