"""Attribute scoping (reference: python/mxnet/attribute.py AttrScope).

``group2ctx``-style attributes attached here become pjit sharding/placement
hints on the TPU build (symbol __ctx_group__ → mesh axis assignment).
"""
from __future__ import annotations

import threading

__all__ = ["AttrScope", "current"]


class AttrScope:
    _tls = threading.local()

    def __init__(self, **kwargs):
        for k, v in kwargs.items():
            if not isinstance(v, str):
                # reference attribute.py: 'Attributes need to be string' —
                # silently stringifying dicts/ints attaches garbage to
                # every symbol in scope
                raise ValueError(
                    f"AttrScope value for {k!r} must be a string, "
                    f"got {type(v).__name__}")
        self._attr = dict(kwargs)

    def get(self, attr):
        out = dict(self._attr)
        if attr:
            for k, v in attr.items():
                if not isinstance(v, str):
                    # same contract as __init__: per-call attr= dicts must
                    # not smuggle non-string values into attr_dict/tojson
                    raise ValueError(
                        f"attr value for {k!r} must be a string, "
                        f"got {type(v).__name__}")
                out[k] = v
        return out

    def __enter__(self):
        stack = AttrScope._stack()
        merged = dict(stack[-1]._attr)
        merged.update(self._attr)
        self._attr = merged
        stack.append(self)
        return self

    def __exit__(self, *exc):
        stack = AttrScope._stack()
        if len(stack) <= 1:
            raise RuntimeError(
                "AttrScope.__exit__ without a matching __enter__")
        stack.pop()

    @staticmethod
    def _stack():
        if not hasattr(AttrScope._tls, "stack"):
            AttrScope._tls.stack = [AttrScope()]
        return AttrScope._tls.stack


def current() -> AttrScope:
    return AttrScope._stack()[-1]
