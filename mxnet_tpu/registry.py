"""Generic class registry factories (reference: python/mxnet/registry.py —
the machinery behind optimizer/metric/initializer registration, exposed so
user code can build its own registered families the same way)."""
from __future__ import annotations

import json
import warnings

from .base import MXNetError

__all__ = ["get_registry", "get_register_func", "get_alias_func",
           "get_create_func"]

_REGISTRIES = {}


def _registry_of(base_class):
    return _REGISTRIES.setdefault(base_class, {})


def get_registry(base_class):
    """A copy of the name → class mapping registered for ``base_class``."""
    return dict(_registry_of(base_class))


def get_register_func(base_class, nickname):
    """Returns register(klass, name=None) — usable plain or as a decorator;
    re-registration warns and replaces (reference semantics)."""
    registry = _registry_of(base_class)

    def register(klass, name=None):
        if not issubclass(klass, base_class):
            raise MXNetError(
                f"can only register subclasses of {base_class.__name__}, "
                f"got {klass}")
        key = (name or klass.__name__).lower()
        if key in registry and registry[key] is not klass:
            warnings.warn(
                f"new {nickname} {klass} registered with name {key} is "
                f"overriding existing {nickname} {registry[key]}")
        registry[key] = klass
        return klass

    register.__doc__ = f"Register a {nickname} class."
    return register


def get_alias_func(base_class, nickname):
    """Returns alias(*names) — a decorator adding extra registry names."""
    register = get_register_func(base_class, nickname)

    def alias(*aliases):
        def reg(klass):
            for name in aliases:
                register(klass, name)
            return klass

        return reg

    return alias


def get_create_func(base_class, nickname):
    """Returns create(name_or_instance_or_json, **kwargs) with the
    reference's three input forms: an instance passes through, a string
    resolves in the registry, a '["name", {kwargs}]' JSON (the dumps()
    format) reconstructs."""
    registry = _registry_of(base_class)

    def create(*args, **kwargs):
        if args and isinstance(args[0], base_class):
            if len(args) > 1 or kwargs:
                raise MXNetError(
                    f"{nickname} instance given: no extra arguments allowed")
            return args[0]
        if not args or not isinstance(args[0], str):
            raise MXNetError(
                f"{nickname} create expects an instance, a registered "
                f"name, or a dumps() JSON string")
        name, rest = args[0], args[1:]
        if name.startswith("["):
            if rest or kwargs:
                raise MXNetError(
                    f"{nickname} JSON spec given: no extra arguments allowed")
            name, kw = json.loads(name)
            return create(name, **kw)
        key = name.lower()
        if key not in registry:
            raise MXNetError(
                f"{nickname} {name!r} is not registered "
                f"(known: {sorted(registry)})")
        return registry[key](*rest, **kwargs)

    create.__doc__ = f"Create a {nickname} instance."
    return create
