"""Fork and crash handlers (reference: src/initialize.cc:40-86 —
pthread_atfork engine stop/restart so DataLoader workers can fork safely,
plus a segfault handler printing a backtrace).

Python analogue: ``os.register_at_fork`` quiesces the native dependency
engine before a fork (its C++ worker threads do not survive into the
child), abandons the child's inherited engine handle without touching the
dead native state (a fresh engine is lazily created on first use), and
reseeds the child's PRNG stream so forked workers don't draw identical
randomness.  ``faulthandler`` covers the segfault-backtrace half.
"""
from __future__ import annotations

import faulthandler
import os

_installed = False


def _before_fork():
    from . import engine

    eng = engine._host_engine
    if eng is not None:
        try:
            eng.wait_all()  # quiesce: no op may straddle the fork
        except Exception:
            pass


def _after_in_child():
    from . import engine

    eng = engine._host_engine
    if eng is not None:
        # the native worker threads died with the fork: drop the handle
        # without running close() (which would join ghosts); leak the tiny
        # native struct — exactly the reference's Engine::Stop-without-join
        # child-side behavior
        eng._h = None
        engine._host_engine = None
    # reseed LAZILY: never touch jax here — creating a PRNGKey would
    # initialize the backend (and dial the exclusive TPU tunnel) inside
    # every forked DataLoader worker.  Drop BOTH the thread-local key and
    # the materialized global base (diverting _DEFAULT_SEED alone is
    # ineffective once _base['key'] exists — every child would re-derive
    # the parent's stream); the next key use rebuilds from the fresh seed.
    from . import random as _random

    if hasattr(_random._state, "key"):
        del _random._state.key
    _random._DEFAULT_SEED = int.from_bytes(os.urandom(4), "little")
    with _random._base_lock:
        _random._base["key"] = None
        _random._base["gen"] += 1
    # numpy's global RNG is NOT auto-reseeded at fork (stdlib random is):
    # the flip/crop transforms draw from it, and correlated workers make
    # identical augmentation decisions
    import numpy as _np

    _np.random.seed(int.from_bytes(os.urandom(4), "little"))


def install():
    global _installed
    if _installed:
        return
    _installed = True
    try:
        faulthandler.enable()
    except Exception:
        pass  # non-main-thread or closed stderr: backtraces just stay off
    os.register_at_fork(before=_before_fork, after_in_child=_after_in_child)
