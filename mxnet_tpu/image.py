"""Image loading + augmentation (reference: python/mxnet/image/image.py —
ImageIter, augmenters; native augmenters src/io/image_aug_default.cc).

Augmenters operate on numpy HWC uint8/float arrays host-side (the reference
decodes/augments on CPU too); batches land on TPU as one async transfer.
"""
from __future__ import annotations

import random as _pyrandom
from typing import List, Optional

import numpy as _np

from .base import MXNetError
from .io import DataBatch, DataDesc, DataIter
from .ndarray import array as nd_array
from . import recordio as _recordio

__all__ = ["imdecode", "imread",
           "imresize", "resize_short", "fixed_crop", "random_crop", "center_crop",
           "color_normalize", "random_size_crop", "Augmenter", "ResizeAug",
           "ForceResizeAug", "RandomCropAug", "RandomSizedCropAug", "CenterCropAug",
           "HorizontalFlipAug", "CastAug", "ColorNormalizeAug", "BrightnessJitterAug",
           "ContrastJitterAug", "SaturationJitterAug", "LightingAug", "ColorJitterAug",
           "CreateAugmenter", "ImageIter", "ImageDetIter", "ImageRecordIterImpl"]


def imdecode(buf, flag=1, to_rgb=True, **kwargs):
    """Decode a compressed image buffer to an HWC uint8 NDArray.

    Reference: mx.image.imdecode (opencv-backed, python/mxnet/image/image.py)
    — flag=0 grayscale, 1 color; to_rgb converts the reference's BGR decode
    order (PIL already yields RGB, so to_rgb=False flips to BGR for parity
    with code expecting the raw cv2 order).
    """
    import io as _io

    from PIL import Image as _PILImage

    img = _PILImage.open(_io.BytesIO(bytes(buf)))
    img = img.convert("L" if int(flag) == 0 else "RGB")
    arr = _np.asarray(img, dtype=_np.uint8)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if int(flag) != 0 and not to_rgb:
        arr = arr[:, :, ::-1]
    return nd_array(arr)


def imread(filename, flag=1, to_rgb=True, **kwargs):
    """Read + decode an image file (reference: mx.image.imread)."""
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb, **kwargs)


def _resize_np(img, h, w, interp=1):
    import jax
    import jax.numpy as jnp

    x = jnp.asarray(img, dtype=jnp.float32)
    out = jax.image.resize(x, (int(h), int(w)) + x.shape[2:],
                           method="linear" if interp else "nearest")
    return _np.asarray(out)


def imresize(src, w, h, interp=1):
    return _resize_np(src, h, w, interp)


def resize_short(src, size, interp=2):
    h, w = src.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return _resize_np(src, new_h, new_w, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = _resize_np(out, size[1], size[0], interp)
    return out


def random_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = size
    x0 = _pyrandom.randint(0, max(0, w - new_w))
    y0 = _pyrandom.randint(0, max(0, h - new_h))
    out = fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = size
    x0 = max(0, (w - new_w) // 2)
    y0 = max(0, (h - new_h) // 2)
    out = fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2):
    h, w = src.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = _pyrandom.uniform(area[0], area[1]) * src_area
        log_ratio = (_np.log(ratio[0]), _np.log(ratio[1]))
        new_ratio = _np.exp(_pyrandom.uniform(*log_ratio))
        new_w = int(round(_np.sqrt(target_area * new_ratio)))
        new_h = int(round(_np.sqrt(target_area / new_ratio)))
        if new_w <= w and new_h <= h:
            x0 = _pyrandom.randint(0, w - new_w)
            y0 = _pyrandom.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    src = src.astype(_np.float32) - mean
    if std is not None:
        src = src / std
    return src


class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return _resize_np(src, self.size[1], self.size[0], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size, self.area, self.ratio, self.interp = size, area, ratio, interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            return src[:, ::-1]
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(typ=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean = _np.asarray(mean, dtype=_np.float32) if mean is not None else None
        self.std = _np.asarray(std, dtype=_np.float32) if std is not None else None

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.brightness, self.brightness)
        return src * alpha


class ContrastJitterAug(Augmenter):
    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast
        self.coef = _np.array([[[0.299, 0.587, 0.114]]], dtype=_np.float32)

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.contrast, self.contrast)
        gray = (src * self.coef).sum()
        # reference image.py:717: 3.0 * (1-alpha) / gray.size — the 3 undoes
        # the channel dimension folded into gray.size
        gray = (3.0 * (1.0 - alpha) / src.size) * gray
        return src * alpha + gray


class SaturationJitterAug(Augmenter):
    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation
        self.coef = _np.array([[[0.299, 0.587, 0.114]]], dtype=_np.float32)

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.saturation, self.saturation)
        gray = (src * self.coef).sum(axis=2, keepdims=True) * (1.0 - alpha)
        return src * alpha + gray


class LightingAug(Augmenter):
    """PCA lighting noise (reference: image.py LightingAug)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = _np.asarray(eigval)
        self.eigvec = _np.asarray(eigvec)

    def __call__(self, src):
        alpha = _np.random.normal(0, self.alphastd, size=(3,))
        rgb = _np.dot(self.eigvec * alpha, self.eigval)
        return src + rgb


class ColorJitterAug(Augmenter):
    def __init__(self, brightness=0, contrast=0, saturation=0):
        super().__init__()
        self.augs = []
        if brightness:
            self.augs.append(BrightnessJitterAug(brightness))
        if contrast:
            self.augs.append(ContrastJitterAug(contrast))
        if saturation:
            self.augs.append(SaturationJitterAug(saturation))

    def __call__(self, src):
        augs = list(self.augs)
        _pyrandom.shuffle(augs)
        for a in augs:
            src = a(src)
        return src


def _color_augmenters(mean=None, std=None, brightness=0, contrast=0,
                      saturation=0, pca_noise=0):
    """The box-invariant color tail shared by CreateAugmenter and the
    detection iterator's default list (color ops never move pixels, so
    they are safe under fixed normalized bboxes)."""
    auglist: List[Augmenter] = []
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None and std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, rand_gray=0, inter_method=2):
    """Reference: image.py CreateAugmenter — same knobs, same order."""
    auglist: List[Augmenter] = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0), (3 / 4.0, 4 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    auglist.extend(_color_augmenters(mean, std, brightness, contrast,
                                     saturation, pca_noise))
    return auglist


class ImageIter(DataIter):
    """Image iterator over .rec files or image lists
    (reference: image.py ImageIter)."""

    def __init__(self, batch_size, data_shape, label_width=1, path_imgrec=None,
                 path_imglist=None, path_root="", shuffle=False, part_index=0,
                 num_parts=1, aug_list=None, imglist=None, data_name="data",
                 label_name="softmax_label", **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.auglist = aug_list if aug_list is not None else CreateAugmenter(
            (1,) + self.data_shape[1:] if len(self.data_shape) == 3 else self.data_shape,
            **{k: v for k, v in kwargs.items()
               if k in ("resize", "rand_crop", "rand_resize", "rand_mirror",
                        "mean", "std", "brightness", "contrast", "saturation",
                        "pca_noise", "inter_method")})
        self.shuffle = shuffle
        self.record = None
        self.imglist = None
        self.path_root = path_root
        self.imgkeys = []
        if path_imgrec:
            idx_path = path_imgrec[:-4] + ".idx"
            self.record = _recordio.MXIndexedRecordIO(idx_path, path_imgrec, "r")
            self.imgkeys = list(self.record.keys)
        elif path_imglist or imglist is not None:
            # reference image.py: .lst lines are "idx \t label... \t relpath";
            # in-memory imglist entries are [label(s)..., path]
            entries = []
            if path_imglist:
                with open(path_imglist) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        if len(parts) < 3:
                            continue
                        entries.append(([float(v) for v in parts[1:-1]],
                                        parts[-1]))
            else:
                for item in imglist:
                    item = list(item) if isinstance(item, (list, tuple)) \
                        else [item]
                    labs = item[:-1]
                    if len(labs) == 1 and hasattr(labs[0], "__len__") and \
                            not isinstance(labs[0], str):
                        labs = [float(v) for v in labs[0]]
                    else:
                        labs = [float(v) for v in labs]
                    entries.append((labs, item[-1]))
            if not entries:
                raise MXNetError("ImageIter: empty image list")
            self.imglist = entries
            self.imgkeys = list(range(len(entries)))
        if num_parts > 1:
            self.imgkeys = self.imgkeys[part_index::num_parts]
        self.data_name = data_name
        self.label_name = label_name
        self.cursor = 0
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name, (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc(self.label_name, shape)]

    def reset(self):
        if self.shuffle:
            _pyrandom.shuffle(self.imgkeys)
        self.cursor = 0

    def _read(self, key):
        """(label, HWC float image) from the rec file or the image list."""
        import os as _os

        if self.record is not None:
            header, img = _recordio.unpack_img(self.record.read_idx(key))
            return header.label, img
        labs, path = self.imglist[key]
        img = imread(_os.path.join(self.path_root, path)).asnumpy() \
            .astype(_np.float32)
        lab = labs[0] if len(labs) == 1 else _np.asarray(labs, _np.float32)
        return lab, img

    def next(self):
        if (self.record is None and self.imglist is None) or \
                self.cursor + self.batch_size > len(self.imgkeys):
            raise StopIteration
        imgs, labels = [], []
        for i in range(self.batch_size):
            key = self.imgkeys[self.cursor + i]
            lab, img = self._read(key)
            for aug in self.auglist:
                img = aug(img)
            if img.ndim == 2:
                img = img[:, :, None]
            imgs.append(_np.transpose(img, (2, 0, 1)))  # HWC→CHW
            labels.append(float(lab) if _np.isscalar(lab) or getattr(lab, "size", 1) == 1
                          else _np.asarray(lab)[:self.label_width])
        self.cursor += self.batch_size
        data = nd_array(_np.stack(imgs).astype(_np.float32))
        label = nd_array(_np.asarray(labels, dtype=_np.float32))
        return DataBatch([data], [label], pad=0)


def ImageRecordIterImpl(path_imgrec=None, data_shape=(3, 224, 224), batch_size=128,
                        shuffle=False, rand_crop=False, rand_mirror=False,
                        mean_r=0, mean_g=0, mean_b=0, std_r=1, std_g=1, std_b=1,
                        preprocess_threads=4, num_parts=1, part_index=0, **kwargs):
    mean = None
    if mean_r or mean_g or mean_b:
        mean = _np.array([mean_r, mean_g, mean_b])
    std = None
    if std_r != 1 or std_g != 1 or std_b != 1:
        std = _np.array([std_r, std_g, std_b])
    return ImageIter(batch_size, data_shape, path_imgrec=path_imgrec,
                     shuffle=shuffle, rand_crop=rand_crop, rand_mirror=rand_mirror,
                     mean=mean, std=std, num_parts=num_parts, part_index=part_index,
                     **kwargs)


class ImageDetIter(ImageIter):
    """Detection image iterator (reference: python/mxnet/image/detection.py
    ImageDetIter — labels are variable-length object lists padded to a fixed
    (max_objects, label_width) block per image; header-array records carry
    [header_width, obj_width, obj0..., obj1...]).

    Label layout per object: [cls, xmin, ymin, xmax, ymax, ...] normalized.
    Batches yield label shape (B, max_objects, obj_width); missing objects
    are -1-padded (the MultiBoxTarget invalid marker).
    """

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 max_objects=16, obj_width=5, rand_mirror=False, **kwargs):
        self.max_objects = int(max_objects)
        self.obj_width = int(obj_width)
        self._det_rand_mirror = rand_mirror
        kwargs.pop("label_width", None)
        for geo in ("rand_crop", "rand_resize"):
            if kwargs.pop(geo, False):
                # cropping moves the box frame; without the reference's
                # bbox-aware DetRandomCropAug the labels would be silently
                # wrong — refuse instead (mirror IS box-aware here)
                raise NotImplementedError(
                    f"ImageDetIter does not support {geo}: only force-resize "
                    "and rand_mirror adjust the normalized boxes correctly")
        if kwargs.get("aug_list") is None:
            # det-safe default (also when the caller passes aug_list=None —
            # falling through to CreateAugmenter's CenterCrop would shift
            # the box frame): FORCE resize to the output size (normalized
            # boxes are invariant to it), then the box-invariant color tail
            # (mean/std/brightness/... keep working like the reference's
            # CreateDetAugmenter)
            color = {k: kwargs.pop(k) for k in
                     ("mean", "std", "brightness", "contrast", "saturation",
                      "pca_noise") if k in kwargs}
            kwargs["aug_list"] = [
                ForceResizeAug((data_shape[2], data_shape[1])), CastAug(),
            ] + _color_augmenters(**color)
        super().__init__(batch_size, data_shape, label_width=1,
                         path_imgrec=path_imgrec, rand_mirror=False, **kwargs)

    @property
    def provide_label(self):
        return [DataDesc(self.label_name,
                         (self.batch_size, self.max_objects, self.obj_width))]

    def _parse_det_label(self, raw):
        """Reference layout: [header_width, obj_width, (header...), objs...]"""
        arr = _np.asarray(raw, dtype=_np.float32).ravel()
        out = _np.full((self.max_objects, self.obj_width), -1.0, _np.float32)
        if arr.size < 2:
            return out
        header_width = int(arr[0])
        obj_width = int(arr[1])
        body = arr[header_width:]
        n = min(body.size // obj_width, self.max_objects)
        objs = body[:n * obj_width].reshape(n, obj_width)
        out[:n, :min(obj_width, self.obj_width)] = \
            objs[:, :min(obj_width, self.obj_width)]
        return out

    def next(self):
        if self.record is None or self.cursor + self.batch_size > len(self.imgkeys):
            raise StopIteration
        imgs, labels = [], []
        for i in range(self.batch_size):
            key = self.imgkeys[self.cursor + i]
            header, img = _recordio.unpack_img(self.record.read_idx(key))
            lab = self._parse_det_label(header.label)
            for aug in self.auglist:
                img = aug(img)
            if self._det_rand_mirror and _pyrandom.random() < 0.5:
                img = img[:, ::-1]
                flipped = lab.copy()
                valid = flipped[:, 0] >= 0
                flipped[valid, 1] = 1.0 - lab[valid, 3]
                flipped[valid, 3] = 1.0 - lab[valid, 1]
                lab = flipped
            if img.ndim == 2:
                img = img[:, :, None]
            imgs.append(_np.transpose(img, (2, 0, 1)))
            labels.append(lab)
        self.cursor += self.batch_size
        return DataBatch([nd_array(_np.stack(imgs).astype(_np.float32))],
                         [nd_array(_np.stack(labels))], pad=0)

    @staticmethod
    def pack_label(objects, header_width=2):
        """Build the reference header-array label for pack_img:
        [header_width, obj_width, obj0..., ...]."""
        objects = _np.asarray(objects, dtype=_np.float32)
        obj_width = objects.shape[1] if objects.ndim == 2 else 0
        return _np.concatenate([
            _np.asarray([header_width, obj_width], _np.float32),
            objects.ravel()])
