# Symbol composition: an mx.symbol is a graph fragment (list of node
# specs + head index) that serializes to the framework's graph JSON
# (mxnet_tpu/symbol/symbol.py tojson format — same nodes/arg_nodes/heads
# layout as the reference nnvm JSON).  Thin by design: ops compose JSON,
# the executor runtime does everything else.

mx.symbol..new <- function(nodes, head) {
  structure(list(nodes = nodes, head = head), class = "mx.symbol")
}

mx.symbol.Variable <- function(name) {
  mx.symbol..new(list(list(op = "null", name = name, attrs = list(),
                           inputs = list())), 1L)
}

# merge rhs graph into lhs node list, return (nodes, index map for rhs)
mx.symbol..merge <- function(nodes, sym) {
  offset <- length(nodes)
  remap <- integer(length(sym$nodes))
  for (i in seq_along(sym$nodes)) {
    node <- sym$nodes[[i]]
    # dedup identical variable nodes by name (shared inputs)
    hit <- 0L
    if (node$op == "null") {
      for (j in seq_along(nodes)) {
        if (nodes[[j]]$op == "null" && nodes[[j]]$name == node$name) {
          hit <- j
          break
        }
      }
    }
    if (hit > 0L) {
      remap[i] <- hit
    } else {
      node$inputs <- lapply(node$inputs, function(e) {
        c(remap[e[[1]]], e[[2]], e[[3]])
      })
      nodes[[length(nodes) + 1L]] <- node
      remap[i] <- length(nodes)
    }
  }
  list(nodes = nodes, remap = remap)
}

mx.symbol..apply <- function(op, name, attrs, in.syms) {
  nodes <- list()
  heads <- list()
  for (s in in.syms) {
    m <- mx.symbol..merge(nodes, s)
    nodes <- m$nodes
    heads[[length(heads) + 1L]] <- c(m$remap[s$head], 0L, 0L)
  }
  nodes[[length(nodes) + 1L]] <-
    list(op = op, name = name, attrs = attrs, inputs = heads)
  mx.symbol..new(nodes, length(nodes))
}

mx.symbol.FullyConnected <- function(data, num_hidden, name) {
  w <- mx.symbol.Variable(paste0(name, "_weight"))
  b <- mx.symbol.Variable(paste0(name, "_bias"))
  mx.symbol..apply("FullyConnected", name,
                   list(num_hidden = as.character(num_hidden)),
                   list(data, w, b))
}

mx.symbol.Activation <- function(data, act_type, name) {
  # attr values are reprs in the native JSON (symbol.py tojson)
  mx.symbol..apply("Activation", name,
                   list(act_type = paste0("'", act_type, "'")), list(data))
}

mx.symbol.SoftmaxOutput <- function(data, name) {
  lab <- mx.symbol.Variable(paste0(name, "_label"))
  mx.symbol..apply("SoftmaxOutput", name, list(), list(data, lab))
}

mx.symbol.arguments <- function(sym) {
  unlist(lapply(Filter(function(n) n$op == "null", sym$nodes),
                function(n) n$name))
}

# minimal JSON emitter (no external deps; values are strings/ints/lists)
mx.symbol..json.str <- function(s) {
  paste0('"', gsub('"', '\\\\"', s), '"')
}

mx.symbol.tojson <- function(sym) {
  node.strs <- character(length(sym$nodes))
  for (i in seq_along(sym$nodes)) {
    n <- sym$nodes[[i]]
    attr.strs <- character(0)
    for (k in names(n$attrs)) {
      attr.strs <- c(attr.strs, paste0(mx.symbol..json.str(k), ": ",
                                       mx.symbol..json.str(n$attrs[[k]])))
    }
    input.strs <- vapply(n$inputs, function(e) {
      paste0("[", e[[1]] - 1L, ", ", e[[2]], ", ", e[[3]], "]")
    }, character(1))
    node.strs[i] <- paste0(
      '{"op": ', mx.symbol..json.str(n$op),
      ', "name": ', mx.symbol..json.str(n$name),
      ', "attrs": {', paste(attr.strs, collapse = ", "),
      '}, "inputs": [', paste(input.strs, collapse = ", "), "]}")
  }
  arg.idx <- which(vapply(sym$nodes, function(n) n$op == "null",
                          logical(1))) - 1L
  paste0('{"nodes": [', paste(node.strs, collapse = ", "),
         '], "arg_nodes": [', paste(arg.idx, collapse = ", "),
         '], "heads": [[', sym$head - 1L, ", 0, 0]]}")
}
