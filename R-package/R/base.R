# Runtime bootstrap.  Reference analogue: R-package/R/zzz.R dyn.loads the
# mxnet C API; here the runtime is libmxtpu_rt.so (cpp/src/pyruntime.cc).

mx.init <- function(lib.path = "") {
  invisible(.Call("mxtpu_r_init", as.character(lib.path)))
}

mx.version <- function() {
  .Call("mxtpu_r_version")
}
