# FeedForward-style training loop (reference analogue:
# R-package/R/model.R mx.model.FeedForward.create — the kv-optimized
# update loop of python model.py:145-177, in R).

mx.model..param.shapes <- function(symbol, data.shape, hidden) {
  # shapes for the fc params of an MLP built with mx.symbol.FullyConnected
  args <- mx.symbol.arguments(symbol)
  shapes <- list()
  prev <- data.shape[2]
  h.i <- 1
  for (a in args) {
    if (grepl("_weight$", a)) {
      shapes[[a]] <- c(hidden[h.i], prev)
      prev <- hidden[h.i]
      h.i <- h.i + 1
    } else if (grepl("_bias$", a)) {
      shapes[[a]] <- prev
    }
  }
  shapes
}

mx.model.FeedForward.create <- function(symbol, X, y, batch.size,
                                        hidden, num.round = 10,
                                        learning.rate = 0.1,
                                        kv.type = "local", verbose = TRUE) {
  n <- nrow(X)
  d <- ncol(X)
  shapes <- mx.model..param.shapes(symbol, c(batch.size, d), hidden)
  params <- list()
  set.seed(0)
  for (nm in names(shapes)) {
    sh <- shapes[[nm]]
    if (length(sh) > 1) {
      params[[nm]] <- mx.nd.array(matrix(
        rnorm(prod(sh), sd = 1 / sqrt(sh[length(sh)])), sh[1], sh[2]))
    } else {
      params[[nm]] <- mx.nd.zeros(sh)
    }
  }

  bind.shapes <- c(list(data = c(batch.size, d)), shapes,
                   list(softmax_label = batch.size))
  exec <- mx.simple.bind(symbol, bind.shapes)

  kv <- mx.kv.create(kv.type)
  mx.kv.set.optimizer(kv, "sgd", learning.rate)
  keys <- seq_along(params)
  for (i in keys) mx.kv.init(kv, i - 1, params[[i]])

  batches <- floor(n / batch.size)
  for (round in seq_len(num.round)) {
    hits <- 0
    for (b in seq_len(batches)) {
      rows <- ((b - 1) * batch.size + 1):(b * batch.size)
      xb <- mx.nd.array(X[rows, , drop = FALSE])
      yb <- mx.nd.array(y[rows])
      mx.exec.set.arg(exec, "data", xb)
      mx.exec.set.arg(exec, "softmax_label", yb)
      for (i in keys) {
        mx.exec.set.arg(exec, names(params)[i], params[[i]])
      }
      mx.exec.forward(exec, TRUE)
      probs <- mx.exec.output(exec, 0L)
      pred <- max.col(matrix(probs$data, batch.size, probs$shape[2],
                             byrow = TRUE)) - 1
      hits <- hits + sum(pred == y[rows])
      mx.exec.backward(exec)
      for (i in keys) {
        nm <- names(params)[i]
        gr <- mx.exec.grad(exec, nm, length(params[[nm]]$data))
        mx.kv.push(kv, i - 1, gr, params[[nm]]$shape)
        params[[nm]]$data <- mx.kv.pull(kv, i - 1,
                                        length(params[[nm]]$data))
      }
    }
    if (verbose) {
      cat(sprintf("round %d: train acc %.4f\n", round,
                  hits / (batches * batch.size)))
    }
  }
  structure(list(symbol = symbol, params = params, exec = exec,
                 batch.size = batch.size), class = "mx.model")
}

mx.model.predict <- function(model, X) {
  bs <- model$batch.size
  n <- nrow(X)
  preds <- integer(0)
  for (b in seq_len(floor(n / bs))) {
    rows <- ((b - 1) * bs + 1):(b * bs)
    mx.exec.set.arg(model$exec, "data",
                    mx.nd.array(X[rows, , drop = FALSE]))
    for (nm in names(model$params)) {
      mx.exec.set.arg(model$exec, nm, model$params[[nm]])
    }
    mx.exec.forward(model$exec, FALSE)
    probs <- mx.exec.output(model$exec, 0L)
    preds <- c(preds, max.col(matrix(probs$data, bs, probs$shape[2],
                                     byrow = TRUE)) - 1)
  }
  preds
}
