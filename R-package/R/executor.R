# Executor surface over mxtpu_exec_* (reference analogue:
# R-package/R/executor.R mx.simple.bind / mx.exec.*).

mx.simple.bind <- function(symbol, shapes) {
  h <- .Call("mxtpu_r_exec_create", mx.symbol.tojson(symbol))
  .Call("mxtpu_r_exec_simple_bind", h, names(shapes),
        lapply(shapes, as.numeric))
  structure(list(handle = h, symbol = symbol), class = "mx.executor")
}

mx.exec.set.arg <- function(exec, name, nd) {
  .Call("mxtpu_r_exec_set_arg", exec$handle, name,
        nd$data, nd$shape)
  invisible(exec)
}

mx.exec.forward <- function(exec, is.train = TRUE) {
  .Call("mxtpu_r_exec_forward", exec$handle, is.train)
  invisible(exec)
}

mx.exec.backward <- function(exec) {
  .Call("mxtpu_r_exec_backward", exec$handle)
  invisible(exec)
}

mx.exec.output <- function(exec, idx = 0L) {
  out <- .Call("mxtpu_r_exec_output", exec$handle, as.integer(idx))
  structure(list(data = out[[1]], shape = out[[2]]), class = "mx.ndarray")
}

mx.exec.grad <- function(exec, name, nelem) {
  .Call("mxtpu_r_exec_grad", exec$handle, name, as.numeric(nelem))
}
