# NDArray surface.  The embedded runtime exchanges flat f32 buffers
# (row-major, C order); on the R side an mx.ndarray is a numeric vector
# with a C-order shape attribute.  R matrices are column-major, so
# converting transposes at the boundary — same convention as the
# reference R binding's mx.nd.array.

mx.nd.array <- function(src) {
  if (is.matrix(src)) {
    shape <- dim(src)
    data <- as.numeric(t(src))          # to C order
  } else {
    shape <- length(src)
    data <- as.numeric(src)
  }
  structure(list(data = data, shape = as.numeric(shape)),
            class = "mx.ndarray")
}

mx.nd.zeros <- function(shape) {
  structure(list(data = numeric(prod(shape)), shape = as.numeric(shape)),
            class = "mx.ndarray")
}

mx.nd.shape <- function(nd) nd$shape
