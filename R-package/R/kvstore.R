# KVStore surface over mxtpu_kv_* (reference analogue:
# R-package/R/kvstore.R; the store runs the optimizer server-side the way
# kv.set.optimizer does in the reference).

mx.kv.create <- function(kind = "local") {
  structure(list(handle = .Call("mxtpu_r_kv_create", kind)),
            class = "mx.kvstore")
}

mx.kv.init <- function(kv, key, nd) {
  .Call("mxtpu_r_kv_init", kv$handle, as.integer(key), nd$data, nd$shape)
  invisible(kv)
}

mx.kv.push <- function(kv, key, data, shape) {
  .Call("mxtpu_r_kv_push", kv$handle, as.integer(key),
        as.numeric(data), as.numeric(shape))
  invisible(kv)
}

mx.kv.pull <- function(kv, key, nelem) {
  .Call("mxtpu_r_kv_pull", kv$handle, as.integer(key), as.numeric(nelem))
}

mx.kv.set.optimizer <- function(kv, name = "sgd", learning.rate = 0.05) {
  .Call("mxtpu_r_kv_set_optimizer", kv$handle, name,
        as.numeric(learning.rate))
  invisible(kv)
}
