/* R .Call shim over the mxnet_tpu embedded-runtime C ABI.
 *
 * Reference analogue: R-package/src/ in the reference wraps its C API for
 * R; here the same role is a ~300-line translation layer onto the
 * mxtpu_rt_* / mxtpu_exec_* / mxtpu_kv_* surface (cpp/include/mxtpu.h,
 * implemented by cpp/src/pyruntime.cc).  Handles are int64 values carried
 * as R doubles (exact for the small ids the runtime issues); R numerics
 * (double) convert to the runtime's float at the boundary.
 *
 * Compiles against real R headers (Rinternals.h) for the installed
 * package, and against tests/r_stub/Rinternals.h for the hermetic CI
 * drive (same source, stubbed R memory model).
 */
#include <Rinternals.h>

#include <dlfcn.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

/* ---- dynamic binding to libmxtpu_rt.so ---------------------------------- */

typedef int (*fn_rt_init)(void);
typedef const char *(*fn_rt_last_error)(void);
typedef int64_t (*fn_exec_create)(const char *);
typedef int (*fn_exec_simple_bind)(int64_t, const char **, const int64_t *,
                                   const int *, int);
typedef int (*fn_exec_set_arg)(int64_t, const char *, const float *,
                               const int64_t *, int);
typedef int (*fn_exec_forward)(int64_t, int);
typedef int (*fn_exec_backward)(int64_t);
typedef int (*fn_exec_num_outputs)(int64_t);
typedef int (*fn_exec_output_shape)(int64_t, int, int64_t *, int *, int);
typedef int (*fn_exec_output)(int64_t, int, float *, int64_t);
typedef int (*fn_exec_grad)(int64_t, const char *, float *, int64_t);
typedef int64_t (*fn_kv_create)(const char *);
typedef int (*fn_kv_init)(int64_t, int, const float *, const int64_t *, int);
typedef int (*fn_kv_push)(int64_t, int, const float *, const int64_t *, int);
typedef int (*fn_kv_pull)(int64_t, int, float *, int64_t);
typedef int (*fn_kv_set_optimizer)(int64_t, const char *, float);
typedef const char *(*fn_version)(void);

static struct {
  void *lib;
  fn_rt_init rt_init;
  fn_rt_last_error rt_last_error;
  fn_exec_create exec_create;
  fn_exec_simple_bind exec_simple_bind;
  fn_exec_set_arg exec_set_arg;
  fn_exec_forward exec_forward;
  fn_exec_backward exec_backward;
  fn_exec_num_outputs exec_num_outputs;
  fn_exec_output_shape exec_output_shape;
  fn_exec_output exec_output;
  fn_exec_grad exec_grad;
  fn_kv_create kv_create;
  fn_kv_init kv_init;
  fn_kv_push kv_push;
  fn_kv_pull kv_pull;
  fn_kv_set_optimizer kv_set_optimizer;
  fn_version version;
} g;

static void *need_sym(const char *name) {
  void *p = dlsym(g.lib, name);
  if (!p) Rf_error("libmxtpu_rt.so: missing symbol %s", name);
  return p;
}

static void check_rc(int rc, const char *what) {
  if (rc != 0)
    Rf_error("%s failed: %s", what,
             g.rt_last_error ? g.rt_last_error() : "(no error fn)");
}

/* mxtpu_r_init(path): dlopen the runtime and initialize the embedded
 * interpreter.  path == "" tries the default lookup. */
SEXP mxtpu_r_init(SEXP path) {
  const char *p = CHAR(STRING_ELT(path, 0));
  if (g.lib == NULL) {
    g.lib = dlopen(p[0] ? p : "libmxtpu_rt.so", RTLD_NOW | RTLD_GLOBAL);
    if (!g.lib) Rf_error("cannot dlopen %s: %s", p, dlerror());
    g.rt_init = (fn_rt_init)need_sym("mxtpu_rt_init");
    g.rt_last_error = (fn_rt_last_error)need_sym("mxtpu_rt_last_error");
    g.exec_create = (fn_exec_create)need_sym("mxtpu_exec_create");
    g.exec_simple_bind =
        (fn_exec_simple_bind)need_sym("mxtpu_exec_simple_bind");
    g.exec_set_arg = (fn_exec_set_arg)need_sym("mxtpu_exec_set_arg");
    g.exec_forward = (fn_exec_forward)need_sym("mxtpu_exec_forward");
    g.exec_backward = (fn_exec_backward)need_sym("mxtpu_exec_backward");
    g.exec_num_outputs =
        (fn_exec_num_outputs)need_sym("mxtpu_exec_num_outputs");
    g.exec_output_shape =
        (fn_exec_output_shape)need_sym("mxtpu_exec_output_shape");
    g.exec_output = (fn_exec_output)need_sym("mxtpu_exec_output");
    g.exec_grad = (fn_exec_grad)need_sym("mxtpu_exec_grad");
    g.kv_create = (fn_kv_create)need_sym("mxtpu_kv_create");
    g.kv_init = (fn_kv_init)need_sym("mxtpu_kv_init");
    g.kv_push = (fn_kv_push)need_sym("mxtpu_kv_push");
    g.kv_pull = (fn_kv_pull)need_sym("mxtpu_kv_pull");
    g.kv_set_optimizer =
        (fn_kv_set_optimizer)need_sym("mxtpu_kv_set_optimizer");
    g.version = (fn_version)dlsym(g.lib, "mxtpu_version");
    check_rc(g.rt_init(), "mxtpu_rt_init");
  }
  return R_NilValue;
}

SEXP mxtpu_r_version(void) {
  return mkString(g.version ? g.version() : "unknown");
}

/* ---- executor ----------------------------------------------------------- */

SEXP mxtpu_r_exec_create(SEXP json) {
  int64_t h = g.exec_create(CHAR(STRING_ELT(json, 0)));
  if (h < 0) check_rc(-1, "mxtpu_exec_create");
  SEXP out = PROTECT(allocVector(REALSXP, 1));
  REAL(out)[0] = (double)h;
  UNPROTECT(1);
  return out;
}

/* names: character vector; shapes: list of numeric vectors (same length) */
SEXP mxtpu_r_exec_simple_bind(SEXP hx, SEXP names, SEXP shapes) {
  int64_t h = (int64_t)asReal(hx);
  int n = (int)XLENGTH(names);
  const char **cnames =
      (const char **)malloc(sizeof(const char *) * (size_t)n);
  int *ndims = (int *)malloc(sizeof(int) * (size_t)n);
  int64_t total = 0;
  for (int i = 0; i < n; ++i) {
    cnames[i] = CHAR(STRING_ELT(names, i));
    ndims[i] = (int)XLENGTH(VECTOR_ELT(shapes, i));
    total += ndims[i];
  }
  int64_t *dims = (int64_t *)malloc(sizeof(int64_t) * (size_t)total);
  int64_t k = 0;
  for (int i = 0; i < n; ++i) {
    SEXP s = VECTOR_ELT(shapes, i);
    for (int d = 0; d < ndims[i]; ++d) dims[k++] = (int64_t)REAL(s)[d];
  }
  int rc = g.exec_simple_bind(h, cnames, dims, ndims, n);
  free(dims);
  free(ndims);
  free((void *)cnames);
  check_rc(rc, "mxtpu_exec_simple_bind");
  return R_NilValue;
}

SEXP mxtpu_r_exec_set_arg(SEXP hx, SEXP name, SEXP data, SEXP shape) {
  int64_t h = (int64_t)asReal(hx);
  int64_t n = (int64_t)XLENGTH(data);
  int ndim = (int)XLENGTH(shape);
  float *buf = (float *)malloc(sizeof(float) * (size_t)n);
  int64_t dims[16];
  for (int64_t i = 0; i < n; ++i) buf[i] = (float)REAL(data)[i];
  for (int d = 0; d < ndim && d < 16; ++d) dims[d] = (int64_t)REAL(shape)[d];
  int rc = g.exec_set_arg(h, CHAR(STRING_ELT(name, 0)), buf, dims, ndim);
  free(buf);
  check_rc(rc, "mxtpu_exec_set_arg");
  return R_NilValue;
}

SEXP mxtpu_r_exec_forward(SEXP hx, SEXP is_train) {
  check_rc(g.exec_forward((int64_t)asReal(hx), asLogical(is_train)),
           "mxtpu_exec_forward");
  return R_NilValue;
}

SEXP mxtpu_r_exec_backward(SEXP hx) {
  check_rc(g.exec_backward((int64_t)asReal(hx)), "mxtpu_exec_backward");
  return R_NilValue;
}

/* returns list(data = numeric, shape = numeric) */
SEXP mxtpu_r_exec_output(SEXP hx, SEXP idx) {
  int64_t h = (int64_t)asReal(hx);
  int i = asInteger(idx);
  int64_t dims[16];
  int ndim = 0;
  check_rc(g.exec_output_shape(h, i, dims, &ndim, 16),
           "mxtpu_exec_output_shape");
  int64_t n = 1;
  for (int d = 0; d < ndim; ++d) n *= dims[d];
  float *buf = (float *)malloc(sizeof(float) * (size_t)n);
  int rc = g.exec_output(h, i, buf, n);
  if (rc != 0) {
    free(buf);
    check_rc(rc, "mxtpu_exec_output");
  }
  SEXP data = PROTECT(allocVector(REALSXP, (R_xlen_t)n));
  for (int64_t j = 0; j < n; ++j) REAL(data)[j] = (double)buf[j];
  free(buf);
  SEXP shape = PROTECT(allocVector(REALSXP, ndim));
  for (int d = 0; d < ndim; ++d) REAL(shape)[d] = (double)dims[d];
  SEXP out = PROTECT(allocVector(VECSXP, 2));
  SET_VECTOR_ELT(out, 0, data);
  SET_VECTOR_ELT(out, 1, shape);
  UNPROTECT(3);
  return out;
}

SEXP mxtpu_r_exec_grad(SEXP hx, SEXP name, SEXP nelem) {
  int64_t h = (int64_t)asReal(hx);
  int64_t n = (int64_t)asReal(nelem);
  float *buf = (float *)malloc(sizeof(float) * (size_t)n);
  int rc = g.exec_grad(h, CHAR(STRING_ELT(name, 0)), buf, n);
  if (rc != 0) {
    free(buf);
    check_rc(rc, "mxtpu_exec_grad");
  }
  SEXP out = PROTECT(allocVector(REALSXP, (R_xlen_t)n));
  for (int64_t j = 0; j < n; ++j) REAL(out)[j] = (double)buf[j];
  free(buf);
  UNPROTECT(1);
  return out;
}

/* ---- kvstore ------------------------------------------------------------ */

SEXP mxtpu_r_kv_create(SEXP kind) {
  int64_t h = g.kv_create(CHAR(STRING_ELT(kind, 0)));
  if (h < 0) check_rc(-1, "mxtpu_kv_create");
  SEXP out = PROTECT(allocVector(REALSXP, 1));
  REAL(out)[0] = (double)h;
  UNPROTECT(1);
  return out;
}

static int kv_data_call(int (*fn)(int64_t, int, const float *,
                                  const int64_t *, int),
                        SEXP hx, SEXP key, SEXP data, SEXP shape) {
  int64_t n = (int64_t)XLENGTH(data);
  int ndim = (int)XLENGTH(shape);
  float *buf = (float *)malloc(sizeof(float) * (size_t)n);
  int64_t dims[16];
  for (int64_t i = 0; i < n; ++i) buf[i] = (float)REAL(data)[i];
  for (int d = 0; d < ndim && d < 16; ++d) dims[d] = (int64_t)REAL(shape)[d];
  int rc = fn((int64_t)asReal(hx), asInteger(key), buf, dims, ndim);
  free(buf);
  return rc;
}

SEXP mxtpu_r_kv_init(SEXP hx, SEXP key, SEXP data, SEXP shape) {
  check_rc(kv_data_call(g.kv_init, hx, key, data, shape), "mxtpu_kv_init");
  return R_NilValue;
}

SEXP mxtpu_r_kv_push(SEXP hx, SEXP key, SEXP data, SEXP shape) {
  check_rc(kv_data_call(g.kv_push, hx, key, data, shape), "mxtpu_kv_push");
  return R_NilValue;
}

SEXP mxtpu_r_kv_pull(SEXP hx, SEXP key, SEXP nelem) {
  int64_t n = (int64_t)asReal(nelem);
  float *buf = (float *)malloc(sizeof(float) * (size_t)n);
  int rc = g.kv_pull((int64_t)asReal(hx), asInteger(key), buf, n);
  if (rc != 0) {
    free(buf);
    check_rc(rc, "mxtpu_kv_pull");
  }
  SEXP out = PROTECT(allocVector(REALSXP, (R_xlen_t)n));
  for (int64_t j = 0; j < n; ++j) REAL(out)[j] = (double)buf[j];
  free(buf);
  UNPROTECT(1);
  return out;
}

SEXP mxtpu_r_kv_set_optimizer(SEXP hx, SEXP name, SEXP lr) {
  check_rc(g.kv_set_optimizer((int64_t)asReal(hx),
                              CHAR(STRING_ELT(name, 0)), (float)asReal(lr)),
           "mxtpu_kv_set_optimizer");
  return R_NilValue;
}
