# R train-MLP parity test (mirrors cpp-package/example/train_mlp.cc):
# synthetic separable task, 2-layer MLP, kv-optimized SGD; final accuracy
# must clear 0.85.  Needs an R toolchain:
#   R CMD INSTALL R-package   (builds src/mxtpu_r.c against real R headers)
#   MXTPU_RT_PLATFORM=cpu MXTPU_RT_HOME=/path/to/repo Rscript tests/train_mlp.R
# The hermetic CI equivalent (no R in the image) drives the same shim via
# tests/r_stub — see tests/test_r_binding.py at the repo root.

library(mxtpu)

mx.init(Sys.getenv("MXTPU_RT_LIB", "cpp/build/libmxtpu_rt.so"))
cat("runtime:", mx.version(), "\n")

B <- 64; D <- 32; C <- 10; N <- 64 * 24
set.seed(0)
wstar <- matrix(rnorm(D * C), D, C)
X <- matrix(runif(N * D), N, D)
y <- max.col(X %*% wstar) - 1

data <- mx.symbol.Variable("data")
fc1 <- mx.symbol.FullyConnected(data, num_hidden = 64, name = "fc1")
act <- mx.symbol.Activation(fc1, act_type = "relu", name = "relu1")
fc2 <- mx.symbol.FullyConnected(act, num_hidden = C, name = "fc2")
net <- mx.symbol.SoftmaxOutput(fc2, name = "softmax")

model <- mx.model.FeedForward.create(net, X, y, batch.size = B,
                                     hidden = c(64, C), num.round = 12,
                                     learning.rate = 0.2)
pred <- mx.model.predict(model, X)
acc <- mean(pred == y[seq_along(pred)])
cat(sprintf("final train accuracy: %.4f\n", acc))
stopifnot(acc > 0.85)
cat("R binding train-MLP parity: OK\n")
