#!/usr/bin/env python
"""Control-flow micro-benchmark (reference: benchmark/python/control_flow/ —
foreach/while_loop vs unrolled timing)."""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import nd


def bench_foreach(T, D, iters):
    x = nd.array(np.random.rand(T, 8, D).astype(np.float32))
    s0 = nd.zeros((8, D))

    def body(xs, states):
        h = states[0]
        return h, [nd.tanh(h + xs)]

    out, st = nd.contrib.foreach(body, x, [s0])  # warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out, st = nd.contrib.foreach(body, x, [s0])
    st[0].wait_to_read()
    return (time.perf_counter() - t0) / iters


def bench_while(T, D, iters):

    def step(i, s):
        return [i + 1, nd.tanh(s + 1.0)]

    i0, s0 = nd.array([0.0]), nd.zeros((8, D))

    def run():
        i, s = i0, s0
        while (i < T).asscalar():
            i, s = step(i, s)
        return s

    run()
    t0 = time.perf_counter()
    for _ in range(iters):
        s = run()
    s.wait_to_read()
    return (time.perf_counter() - t0) / iters


def bench_foreach_compiled(T, D, iters):
    """The traceable path: foreach lowers to one lax.scan inside one XLA
    program (sym.contrib.foreach / hybridize both take it)."""
    import jax

    x = np.random.rand(T, 8, D).astype(np.float32)
    s0 = np.zeros((8, D), np.float32)

    def body(xs, states):
        h = states[0]
        return h, [nd.tanh(h + xs)]

    def step(xv, sv):
        out, st = nd.contrib.foreach(body, nd.NDArray(xv), [nd.NDArray(sv)])
        # return the stacked outputs too, or XLA dead-code-eliminates the
        # per-step stacking the eager benchmark pays for
        return out._data, st[0]._data

    jstep = jax.jit(step)
    jstep(x, s0)[1].block_until_ready()  # warm/compile
    t0 = time.perf_counter()
    for _ in range(iters):
        o, r = jstep(x, s0)
    o.block_until_ready()
    r.block_until_ready()
    return (time.perf_counter() - t0) / iters


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("-T", type=int, default=32)
    parser.add_argument("-D", type=int, default=64)
    parser.add_argument("--iters", type=int, default=10)
    args = parser.parse_args()
    print(f"foreach eager    T={args.T}: {bench_foreach(args.T, args.D, args.iters)*1e3:.2f} ms")
    print(f"foreach compiled T={args.T}: {bench_foreach_compiled(args.T, args.D, args.iters)*1e3:.2f} ms")
    print(f"while            T={args.T}: {bench_while(args.T, args.D, args.iters)*1e3:.2f} ms")
