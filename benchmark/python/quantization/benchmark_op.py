"""Quantized-op micro-benchmark: int8 vs fp32 conv / FC throughput.

Reference: benchmark/python/quantization/benchmark_op.py (quantized_conv
speedup table).  Prints op, shape, fp32 ms, int8 ms, speedup.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", ".."))

import time

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd


def timed(fn, iters=20):
    fn().wait_to_read()
    t0 = time.perf_counter()
    out = None
    for _ in range(iters):
        out = fn()
    out.wait_to_read()
    return (time.perf_counter() - t0) / iters


def bench_conv(batch, cin, hw, cout, kernel):
    rng = np.random.RandomState(0)
    x = nd.array(rng.rand(batch, cin, hw, hw).astype(np.float32))
    w = nd.array(rng.rand(cout, cin, kernel, kernel).astype(np.float32) * 0.1)
    qx, xmin, xmax = nd.contrib.quantize(x, nd.array([0.0]), nd.array([1.0]))
    qw, wmin, wmax = nd.contrib.quantize(w, nd.array([0.0]), nd.array([0.1]))

    t_fp = timed(lambda: nd.Convolution(
        x, w, kernel=(kernel, kernel), num_filter=cout, no_bias=True))
    t_q = timed(lambda: nd.contrib.quantized_conv(
        qx, qw, xmin, xmax, wmin, wmax, kernel=(kernel, kernel),
        num_filter=cout, no_bias=True)[0])
    print(f"conv {batch}x{cin}x{hw}x{hw} -> {cout} k{kernel}: "
          f"fp32 {t_fp*1e3:7.2f} ms  int8 {t_q*1e3:7.2f} ms  "
          f"speedup {t_fp/t_q:4.2f}x")


def bench_fc(batch, cin, cout):
    rng = np.random.RandomState(0)
    x = nd.array(rng.rand(batch, cin).astype(np.float32))
    w = nd.array(rng.rand(cout, cin).astype(np.float32) * 0.1)
    qx, xmin, xmax = nd.contrib.quantize(x, nd.array([0.0]), nd.array([1.0]))
    qw, wmin, wmax = nd.contrib.quantize(w, nd.array([0.0]), nd.array([0.1]))

    t_fp = timed(lambda: nd.FullyConnected(x, w, num_hidden=cout,
                                           no_bias=True))
    t_q = timed(lambda: nd.contrib.quantized_fully_connected(
        qx, qw, xmin, xmax, wmin, wmax, num_hidden=cout, no_bias=True)[0])
    print(f"fc   {batch}x{cin} -> {cout}: "
          f"fp32 {t_fp*1e3:7.2f} ms  int8 {t_q*1e3:7.2f} ms  "
          f"speedup {t_fp/t_q:4.2f}x")


if __name__ == "__main__":
    import jax

    print("device:", mx.context.current_context())
    if jax.default_backend() == "tpu":
        conv_shapes = [(32, 64, 56, 64, 3), (32, 128, 28, 128, 3),
                       (32, 256, 14, 256, 3)]
        fc_shapes = [(64, 512, 512), (64, 1024, 1024)]
    else:  # CPU smoke sizes: the numbers only matter on the chip
        conv_shapes = [(4, 16, 14, 16, 3)]
        fc_shapes = [(16, 128, 128)]
    for shape in conv_shapes:
        bench_conv(*shape)
    for shape in fc_shapes:
        bench_fc(*shape)
