#!/usr/bin/env python
"""Transformer-LM throughput bench: local vs flash attention, single chip
(no reference twin — the 2018 codebase has no transformer; SURVEY §5.7
makes long-context first-class and this measures its two attention legs).

Prints one JSON line per configuration with tokens/sec (chained-args
timing: each step consumes the previous step's params so nothing can be
elided — same discipline as bench.py / tools/perf_sweep.py).

CPU smoke: --smoke (tiny shapes, validates the harness hermetically).
On a TPU host run as-is; flash streams k/v through VMEM so the memory
ceiling is O(T) and long sequences fit where dense attention OOMs.
"""
import argparse
import functools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", ".."))

import jax
import jax.numpy as jnp

BF16 = os.environ.get("LM_BENCH_BF16", "1") == "1"

from mxnet_tpu.ops.flash_attention import flash_attention
from mxnet_tpu.parallel import transformer as tr
from mxnet_tpu.parallel.ring_attention import local_attention


def bench_step(cfg, B, T, attention, steps):
    params = tr.transformer_lm_init(cfg, jax.random.PRNGKey(0))
    rs = np.random.RandomState(0)
    tokens = jnp.asarray(rs.randint(0, cfg.vocab, (B, T)).astype(np.int32))
    labels = jnp.asarray(np.roll(np.asarray(tokens), -1, 1))
    positions = jnp.arange(T, dtype=jnp.int32)

    step = jax.jit(functools.partial(
        tr.train_step, cfg=cfg, lr=0.1, attention=attention,
        compute_dtype=jnp.bfloat16 if BF16 else None),
        donate_argnums=(0, 1))
    momenta = {k: jnp.zeros_like(v) for k, v in params.items()}
    t0 = time.perf_counter()
    loss, params, momenta = step(params, momenta, tokens, labels, positions)
    float(loss)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(steps):
        loss, params, momenta = step(params, momenta, tokens, labels,
                                     positions)
    float(loss)
    dt = time.perf_counter() - t0
    return B * T * steps / dt, compile_s


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=2048)
    ap.add_argument("--d-model", type=int, default=512)
    ap.add_argument("--n-heads", type=int, default=8)
    ap.add_argument("--n-layers", type=int, default=4)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes; hermetic CPU harness validation")
    args = ap.parse_args()
    if args.smoke:
        args.batch, args.seq_len, args.d_model = 2, 128, 64
        args.n_heads, args.n_layers, args.steps = 2, 2, 2

    cfg = tr.TransformerConfig(
        vocab=1024, d_model=args.d_model, n_heads=args.n_heads,
        n_layers=args.n_layers, d_ff=4 * args.d_model,
        max_len=args.seq_len)
    print(f"backend: {jax.default_backend()} bf16={BF16}", file=sys.stderr)
    for name, att in [("local", functools.partial(local_attention,
                                                  causal=True)),
                      ("flash", functools.partial(flash_attention,
                                                  causal=True))]:
        try:
            toks, compile_s = bench_step(cfg, args.batch, args.seq_len,
                                         att, args.steps)
            print(json.dumps({
                "metric": f"transformer_lm_{name}", "value": round(toks, 1),
                "unit": "tokens/sec",
                "B": args.batch, "T": args.seq_len,
                "d_model": args.d_model, "layers": args.n_layers,
                "compile_s": round(compile_s, 1)}), flush=True)
        except Exception as e:  # OOM at long T is a RESULT for dense attn
            print(json.dumps({
                "metric": f"transformer_lm_{name}", "value": None,
                "error": f"{type(e).__name__}: {e}"[:160]}), flush=True)


if __name__ == "__main__":
    main()
