#!/usr/bin/env python
"""Sparse op micro-benchmark (reference: benchmark/python/sparse/ —
dot(csr, dense), row_sparse pull timing)."""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ndarray import sparse as sp


def bench(fn, iters=20):
    fn()
    nd.waitall()  # warm-up fully drained before the timed window
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    nd.waitall()  # include the last iteration's device work
    return (time.perf_counter() - t0) / iters


def main(args):
    rs = np.random.RandomState(0)
    dense = rs.rand(args.rows, args.cols).astype(np.float32)
    mask = rs.rand(args.rows, args.cols) < args.density
    sparse_np = (dense * mask).astype(np.float32)
    csr = sp.csr_matrix(sparse_np)
    rhs = nd.array(rs.rand(args.cols, 64).astype(np.float32))
    t = bench(lambda: nd.dot(csr, rhs))
    print(f"dot(csr {args.rows}x{args.cols} d={args.density}, dense x64): "
          f"{t*1e3:.2f} ms")

    kv = mx.kv.create("local")
    emb = rs.rand(args.rows, 64).astype(np.float32)
    kv.init("emb", nd.array(emb))
    out = nd.zeros((args.rows, 64))
    n_pull = min(256, args.rows)
    row_ids = nd.array(rs.choice(args.rows, n_pull, replace=False)
                       .astype(np.float32))
    t = bench(lambda: kv.row_sparse_pull("emb", out=out, row_ids=row_ids))
    print(f"row_sparse_pull {n_pull}/{args.rows} rows x64: {t*1e3:.2f} ms")


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--rows", type=int, default=10000)
    parser.add_argument("--cols", type=int, default=1000)
    parser.add_argument("--density", type=float, default=0.01)
    args = parser.parse_args()
    main(args)
